"""Standalone server, CLI, and observability.

Mirrors the reference's server/CLI surface (reference: FiloServer.scala
startup ordering, CliMain.scala commands, KamonLogger reporters,
SimpleProfiler.java)."""

import json
import socket
import time
import urllib.request

import numpy as np
import pytest

from filodb_tpu.cli import main as cli_main
from filodb_tpu.standalone import FiloServer
from filodb_tpu.utils.observability import (REGISTRY, TRACER, MetricsRegistry,
                                            SimpleProfiler, Tracer,
                                            span_log_reporter)

BASE = 1_700_000_000_000


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total")
        c.inc(dataset="prom")
        c.inc(2, dataset="prom")
        assert c.value(dataset="prom") == 3
        g = reg.gauge("mem_bytes")
        g.set(42.5, shard="0")
        assert g.value(shard="0") == 42.5
        g.set_fn(lambda: 7.0, shard="1")
        assert g.value(shard="1") == 7.0
        h = reg.histogram("latency_seconds")
        h.observe(0.003)
        h.observe(0.2)
        text = reg.expose_text()
        assert 'reqs_total{dataset="prom"} 3' in text
        assert "# TYPE latency_seconds histogram" in text
        assert 'latency_seconds_bucket{le="+Inf"} 2' in text
        assert "latency_seconds_count 2" in text

    def test_same_name_same_metric(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")


class TestTracer:
    def test_nested_spans_report_parent(self):
        tracer = Tracer()
        records = []
        tracer.add_reporter(records.append)
        with tracer.span("outer", dataset="prom"):
            with tracer.span("inner"):
                pass
        assert [r.name for r in records] == ["inner", "outer"]
        assert records[0].parent == "outer"
        assert records[1].parent is None
        assert records[1].tags == {"dataset": "prom"}

    def test_span_error_recorded(self):
        tracer = Tracer()
        records = []
        tracer.add_reporter(records.append)
        with pytest.raises(ValueError):
            with tracer.span("bad"):
                raise ValueError("boom")
        assert "boom" in records[0].error

    def test_log_reporter_formats(self):
        lines = []
        rep = span_log_reporter(lines.append)
        tracer = Tracer()
        tracer.add_reporter(rep)
        with tracer.span("x", shard=3):
            pass
        assert lines and "span x" in lines[0] and "shard=3" in lines[0]


class TestProfiler:
    def test_samples_and_reports(self):
        prof = SimpleProfiler(sample_interval_s=0.002,
                              report_interval_s=3600)
        prof.start()
        t0 = time.time()
        while time.time() - t0 < 0.2:
            sum(i * i for i in range(1000))
        prof.stop()
        rep = prof.report()
        assert "samples" in rep
        assert prof.snapshot()  # captured at least one frame


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    data_dir = str(tmp_path_factory.mktemp("filodb"))
    config = {
        "node": "test-node",
        "data-dir": data_dir,
        "gateway-port": 0,
        "datasets": [{"name": "prom", "num-shards": 4, "min-num-nodes": 1,
                      "schema": "gauge", "spread": 1,
                      "store": {"groups-per-shard": 4}}],
    }
    srv = FiloServer(config)
    port = srv.start()
    yield srv, port
    srv.shutdown()


class TestFiloServer:
    def test_full_node_influx_to_promql(self, server):
        """One process end to end: Influx TCP -> ingestion threads ->
        PromQL over HTTP (the FiloServer.main wiring)."""
        srv, port = server
        gw_port = srv.gateways[0].port
        lines = []
        for i in range(5):
            for k in range(30):
                ts_ns = (BASE + k * 10_000) * 1_000_000
                lines.append(
                    f"node_cpu,_ws_=demo,_ns_=App-0,instance=i{i} "
                    f"value={50 + i + 0.1 * k} {ts_ns}")
        with socket.create_connection(("127.0.0.1", gw_port),
                                      timeout=10) as sk:
            sk.sendall(("\n".join(lines) + "\n").encode())
        deadline = time.time() + 15
        rows = 0
        while time.time() < deadline and rows < 150:
            rows = sum(sh.stats.rows_ingested
                       for sh in srv.memstore.shards("prom"))
            time.sleep(0.05)
        assert rows == 150
        qs = urllib.parse.urlencode({
            "query": 'count(node_cpu{_ws_="demo",_ns_="App-0"})',
            "start": BASE / 1000, "end": (BASE + 290_000) / 1000,
            "step": "30s"})
        url = f"http://127.0.0.1:{port}/promql/prom/api/v1/query_range?{qs}"
        body = json.loads(urllib.request.urlopen(url, timeout=60).read())
        assert body["status"] == "success"
        vals = body["data"]["result"][0]["values"]
        assert any(v == "5" for _, v in vals)

    def test_health_and_metrics_routes(self, server):
        srv, port = server
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/__health", timeout=30).read())
        assert body["healthy"] is True
        assert len(body["shards"]["prom"]) == 4
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
        assert "filodb_node_up" in text

    def test_flush_persists_to_disk(self, server):
        srv, port = server
        n = srv.flush_all()
        assert n > 0
        assert srv.colstore.num_chunks("prom", 0) + \
            srv.colstore.num_chunks("prom", 1) + \
            srv.colstore.num_chunks("prom", 2) + \
            srv.colstore.num_chunks("prom", 3) > 0


import urllib.parse  # noqa: E402  (used above)


class TestCli:
    def test_create_list(self, tmp_path, capsys):
        d = str(tmp_path)
        assert cli_main(["create", "--data-dir", d, "--dataset", "events",
                         "--num-shards", "8"]) == 0
        assert cli_main(["list", "--data-dir", d]) == 0
        out = capsys.readouterr().out
        assert "events" in out

    def test_importcsv_and_persisted(self, tmp_path, capsys):
        d = str(tmp_path)
        csv_file = tmp_path / "data.csv"
        csv_file.write_text(
            "timestamp,value,metric,host,_ws_,_ns_\n" + "\n".join(
                f"{BASE + i * 10_000},{i * 1.5},disk_io,h{i % 2},demo,ns"
                for i in range(50)))
        assert cli_main(["importcsv", "--data-dir", d, "--dataset", "ev",
                         "--file", str(csv_file),
                         "--tag-columns", "metric,host,_ws_,_ns_"]) == 0
        out = capsys.readouterr().out
        assert "imported 50 rows" in out
        from filodb_tpu.store.persistence import DiskColumnStore
        disk = DiskColumnStore(f"{d}/chunks.db")
        assert disk.num_chunks("ev", 0) > 0

    def test_partkey_roundtrip(self, capsys):
        from filodb_tpu.core.record import canonical_partkey
        tags = {"_metric_": "up", "job": "api"}
        hexpk = canonical_partkey(tags).hex()
        assert cli_main(["partkey", hexpk]) == 0
        assert json.loads(capsys.readouterr().out) == tags
        assert cli_main(["make-partkey", json.dumps(tags)]) == 0
        assert capsys.readouterr().out.strip() == hexpk

    def test_decode_vector(self, capsys):
        from filodb_tpu.codecs import deltadelta
        ts = (BASE + np.arange(10) * 10_000).astype(np.int64)
        hexblob = deltadelta.encode(ts).hex()
        assert cli_main(["decode-vector", hexblob, "--limit", "3"]) == 0
        out = capsys.readouterr().out
        assert str(BASE) in out

    def test_query_against_live_server(self, server, capsys):
        srv, port = server
        assert cli_main(["labelvalues", "--server",
                         f"http://127.0.0.1:{port}", "--dataset", "prom",
                         "instance"]) == 0
        out = capsys.readouterr().out
        assert "i0" in out
