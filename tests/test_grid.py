"""Aligned-grid leaf kernels vs the general windows implementation.

The grid layout invariant ([B, S] time-major: row c holds the sample
with ts in (t0+(c-1)*gstep, t0+c*gstep]) makes rate windows static
slices; these
tests prove the fast path is semantically identical to
filodb_tpu.ops.windows.rate/increase (which the oracle-backed
tests/test_windows.py already validates against the reference's
RateFunctions semantics).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from filodb_tpu.ops import windows
from filodb_tpu.ops.grid import (GridQuery, rate_grid, rate_grid_grouped,
                                 rate_grid_ref, supports_grid)


def _clip(ts, vals):
    """Apply the kernel layout contract: row 0 = first bucket of the
    first window (drop the pre-window bucket the generator emits)."""
    return ts[1:], vals[1:]

STEP = 60_000
T0 = 600_000
B = 40          # bucket columns
K = 5           # window = 5 buckets


def _aligned_data(n_series=64, seed=0, gap_frac=0.15, reset_frac=0.05):
    """[B, S] grid honoring the layout invariant, with NaN gaps and
    counter resets."""
    rng = np.random.default_rng(seed)
    base = (np.arange(B, dtype=np.int64) * STEP + T0 - STEP + 1)[:, None]
    jitter = rng.integers(0, STEP - 1, size=(B, n_series))
    ts = (base + jitter).astype(np.int64)
    incr = rng.random((B, n_series)) * 10.0
    vals = np.cumsum(incr, axis=0)
    resets = rng.random((B, n_series)) < reset_frac
    # a reset drops the counter back near zero from that row on
    for s in range(n_series):
        for c in np.where(resets[:, s])[0]:
            vals[c:, s] -= vals[c, s] * 0.9
    vals = vals.astype(np.float64)
    gaps = rng.random((B, n_series)) < gap_frac
    vals[gaps] = np.nan
    return jnp.asarray(ts), jnp.asarray(vals)


def _steps(n=None):
    first = T0 + K * STEP
    last = T0 + (B - 1) * STEP
    s = np.arange(first, last + 1, STEP, dtype=np.int64)
    return jnp.asarray(s if n is None else s[:n])


class TestGridRef:
    """Portable reference implementation vs windows.rate (exact)."""

    @pytest.mark.parametrize("is_rate", [True, False])
    def test_matches_windows(self, is_rate):
        ts, vals = _aligned_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      is_rate=is_rate)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        # the general path sees DENSE samples (read_range drops gaps);
        # compact each series and pad trailing rows like scan_batch does
        tsn, vn = np.asarray(cts), np.asarray(cvals)
        S = tsn.shape[1]
        dense_ts = np.full((S, tsn.shape[0]), 2**60, np.int64)
        dense_v = np.full((S, tsn.shape[0]), np.nan)
        for s in range(S):
            keep = np.isfinite(vn[:, s])
            k = keep.sum()
            dense_ts[s, :k] = tsn[keep, s]
            dense_v[s, :k] = vn[keep, s]
        fn = windows.rate if is_rate else windows.increase
        want = np.asarray(fn(jnp.asarray(dense_ts),
                             jnp.asarray(dense_v, dtype=jnp.float32), steps,
                             jnp.asarray(K * STEP, jnp.int64))).T
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=2e-5)

    def test_all_nan_series(self):
        ts, vals = _aligned_data(n_series=8)
        vals = vals.at[:, 3].set(jnp.nan)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        assert np.isnan(got[:, 3]).all()

    def test_single_sample_windows_are_nan(self):
        """n < 2 in a window -> no rate (reference: extrapolatedRate
        requires two samples)."""
        ts, vals = _aligned_data(n_series=4, gap_frac=0.0)
        # first window covers cols 1..K; keep only col K finite in series 0
        vals = vals.at[1:K, 0].set(jnp.nan)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        assert np.isnan(got[0, 0])

    def test_reset_after_gap_matches_dense_path(self):
        """A counter reset right after a missed scrape: the grid holds a
        NaN hole where the dense general path holds adjacent samples; the
        correction must still fire (regression: prev-compare against NaN
        silently skipped it)."""
        n = 16
        base = (np.arange(B, dtype=np.int64) * STEP + T0 - STEP + 1)[:, None]
        ts = (base + 10_000 + np.zeros((B, n), np.int64))
        vals = np.cumsum(np.full((B, n), 7.0), axis=0)
        vals[10:, :] -= vals[10, 0] - 1.0          # reset at row 10
        vals[9, :] = np.nan                        # missed scrape before it
        tsj = jnp.asarray(ts)
        vj = jnp.asarray(vals)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        cts, cvals = _clip(tsj, vj)
        got = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        # dense oracle: drop the NaN row entirely (what read_range yields)
        keep = ~np.isnan(vals[:, 0])
        dts = jnp.asarray(ts[keep][1:].T)
        dvals = jnp.asarray(vals[keep][1:].T)
        want = np.asarray(windows.rate(dts, dvals, steps,
                                       jnp.asarray(K * STEP, jnp.int64))).T
        both = np.isfinite(got) & np.isfinite(want)
        assert both.any()
        np.testing.assert_allclose(got[both], want[both], rtol=2e-5)

    def test_supports_grid(self):
        assert supports_grid(300_000, 60_000, 60_000)
        assert not supports_grid(300_000, 30_000, 60_000)   # step != gstep
        assert not supports_grid(290_000, 60_000, 60_000)   # non-multiple

    def test_auto_falls_back_off_tpu(self):
        ts, vals = _clip(*_aligned_data(n_series=16))
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        from filodb_tpu.ops.grid import rate_grid_auto
        got = np.asarray(rate_grid_auto(ts, vals.astype(jnp.float32),
                                        int(steps[0]), q))
        want = np.asarray(rate_grid_ref(ts, vals.astype(jnp.float32),
                                        int(steps[0]), q))
        np.testing.assert_array_equal(np.isfinite(got), np.isfinite(want))

    def test_shape_validation(self):
        ts, vals = _clip(*_aligned_data(n_series=16))
        ts = ts.astype(jnp.int32)
        vals = vals.astype(jnp.float32)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        with pytest.raises(ValueError, match="multiple of lanes"):
            rate_grid(ts, vals, int(steps[0]), q, lanes=1024)
        with pytest.raises(ValueError, match="group count"):
            rate_grid_grouped(ts, vals, int(steps[0]), q, group_lanes=16)
        with pytest.raises(ValueError, match="rows"):
            rate_grid(ts[:3], vals[:3], int(steps[0]), q, lanes=16,
                      interpret=True)


class TestGridPallasInterpret:
    """Pallas kernels in interpreter mode (no TPU needed) vs the
    portable reference."""

    def _data128(self):
        ts, vals = _clip(*_aligned_data(n_series=128))
        return ts.astype(jnp.int32), vals.astype(jnp.float32)

    def test_series_kernel(self):
        ts, vals = self._data128()
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        want = np.asarray(rate_grid_ref(ts, vals, int(steps[0]), q))
        got = np.asarray(rate_grid(ts, vals, int(steps[0]), q,
                                   lanes=128, interpret=True))
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got)
        # the in-kernel log-step scan associates the correction cumsum
        # differently from jnp.cumsum: f32 round-off only
        np.testing.assert_allclose(got[both], want[both], rtol=5e-5,
                                   atol=1e-6)

    def test_grouped_kernel(self):
        ts, vals = self._data128()
        # 8 groups x 16 lanes
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True)
        s, c = rate_grid_grouped(ts, vals, int(steps[0]), q,
                                 group_lanes=16, interpret=True)
        r = np.asarray(rate_grid_ref(ts, vals, int(steps[0]), q))
        s, c = np.asarray(s), np.asarray(c)
        for g in range(8):
            rg = r[:, g * 16:(g + 1) * 16]
            ok = np.isfinite(rg)
            np.testing.assert_allclose(s[g], np.where(ok, rg, 0).sum(axis=1),
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_array_equal(c[g], ok.sum(axis=1))


class TestGridAggOps:
    """The *_over_time family + instant-selector 'last' on the aligned
    grid vs the general windows kernels (exact semantics match)."""

    @pytest.mark.parametrize("op,wfn", [
        ("sum", "sum_over_time"), ("count", "count_over_time"),
        ("avg", "avg_over_time"), ("last", "last_sample")])
    def test_matches_windows(self, op, wfn):
        ts, vals = _aligned_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        tsn, vn = np.asarray(cts), np.asarray(cvals)
        S = tsn.shape[1]
        dense_ts = np.full((S, tsn.shape[0]), 2**60, np.int64)
        dense_v = np.full((S, tsn.shape[0]), np.nan)
        for s in range(S):
            keep = np.isfinite(vn[:, s])
            k = keep.sum()
            dense_ts[s, :k] = tsn[keep, s]
            dense_v[s, :k] = vn[keep, s]
        fn = getattr(windows, wfn)
        want = np.asarray(fn(jnp.asarray(dense_ts),
                             jnp.asarray(dense_v), steps,
                             jnp.asarray(K * STEP, jnp.int64)))
        if want.ndim == 3:          # last_sample returns (value, ts) pair
            want = want[0]
        want = want.T
        assert (np.isfinite(got) == np.isfinite(want)).all(), op
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-12)

    @pytest.mark.parametrize("op,wfn", [
        ("min", "min_over_time"), ("max", "max_over_time")])
    def test_minmax_matches_windows(self, op, wfn):
        ts, vals = _aligned_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        tsn, vn = np.asarray(cts), np.asarray(cvals)
        S = tsn.shape[1]
        dense_ts = np.full((S, tsn.shape[0]), 2**60, np.int64)
        dense_v = np.full((S, tsn.shape[0]), np.nan)
        for s in range(S):
            keep = np.isfinite(vn[:, s])
            k = keep.sum()
            dense_ts[s, :k] = tsn[keep, s]
            dense_v[s, :k] = vn[keep, s]
        from filodb_tpu.query import rangefns as rf
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        fn = getattr(windows, wfn)
        want = np.asarray(fn(jnp.asarray(dense_ts), jnp.asarray(dense_v),
                             steps, jnp.asarray(K * STEP, jnp.int64),
                             wmax)).T
        assert (np.isfinite(got) == np.isfinite(want)).all(), op
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-12)

    @pytest.mark.parametrize("op", ["sum", "count", "avg", "min", "max",
                                    "last"])
    def test_pallas_interpret_matches_ref(self, op):
        from filodb_tpu.ops.grid import rate_grid
        ts, vals = _aligned_data(n_series=128)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op)
        cts, cvals = _clip(ts, vals)
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(steps[0])), q, lanes=128,
                                   interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all()
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=1e-6)


def _dense_data(n_series=128, n_empty=16, seed=3, reset_frac=0.05):
    """Data satisfying the dense-lane contract: every lane fully finite
    over all rows, except the last ``n_empty`` lanes which are all-NaN
    (the device store's padding / unrequested lanes)."""
    ts, vals = _aligned_data(n_series=n_series, seed=seed, gap_frac=0.0,
                             reset_frac=reset_frac)
    vals = vals.at[:, n_series - n_empty:].set(jnp.nan)
    return _clip(ts, vals)


class TestGridMomentOps:
    """stddev/stdvar on the grid vs the general windows kernels (both
    use grand-mean-centered moments, so results match tightly)."""

    @pytest.mark.parametrize("op,wfn", [
        ("stdvar", "stdvar_over_time"), ("stddev", "stddev_over_time")])
    @pytest.mark.parametrize("gap_frac", [0.0, 0.15])
    def test_matches_windows(self, op, wfn, gap_frac):
        ts, vals = _aligned_data(gap_frac=gap_frac)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        fn = getattr(windows, wfn)
        want = np.asarray(fn(jnp.asarray(dense_ts), jnp.asarray(dense_v),
                             steps, jnp.asarray(K * STEP, jnp.int64))).T
        assert (np.isfinite(got) == np.isfinite(want)).all(), op
        both = np.isfinite(got) & np.isfinite(want)
        # summation order differs (K-slice loop vs prefix scans); near-
        # zero variances amplify the rounding through sqrt -> atol
        np.testing.assert_allclose(got[both], want[both], rtol=1e-7,
                                   atol=1e-5)


class TestGridRegressionOps:
    """deriv / predict_linear / z_score on the grid vs the general
    windows kernels (least-squares + moment semantics)."""

    @pytest.mark.parametrize("gap_frac", [0.0, 0.15])
    def test_deriv_matches_windows(self, gap_frac):
        from filodb_tpu.query import rangefns as rf
        ts, vals = _aligned_data(gap_frac=gap_frac)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="deriv")
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.deriv(jnp.asarray(dense_ts),
                                        jnp.asarray(dense_v), steps,
                                        jnp.asarray(K * STEP, jnp.int64),
                                        wmax)).T
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-6,
                                   atol=1e-9)

    def test_predict_linear_matches_windows(self):
        from filodb_tpu.query import rangefns as rf
        ts, vals = _aligned_data(gap_frac=0.1)
        steps = _steps()
        horizon = 600.0
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="predict_linear", farg=horizon)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.predict_linear(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64), wmax, horizon)).T
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-6,
                                   atol=1e-7)

    @pytest.mark.parametrize("gap_frac", [0.0, 0.15])
    def test_z_score_matches_windows(self, gap_frac):
        ts, vals = _aligned_data(gap_frac=gap_frac)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="zscore")
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        want = np.asarray(windows.z_score(jnp.asarray(dense_ts),
                                          jnp.asarray(dense_v), steps,
                                          jnp.asarray(K * STEP,
                                                      jnp.int64))).T
        # both paths now apply the n >= 2 guard (a single sample's sd is
        # exactly 0 mathematically; rounding noise must not leak a
        # finite garbage z) — masks must agree exactly
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-6,
                                   atol=1e-7)

    @pytest.mark.parametrize("op", ["deriv", "predict_linear", "zscore"])
    def test_pallas_interpret(self, op):
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op,
                      farg=300.0)
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(steps[0])), q, lanes=128,
                                   interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all(), op
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=1e-3,
                                   atol=1e-3)


def _compact(cts, cvals):
    """Per-series NaN compaction: the layout the general kernels see."""
    tsn, vn = np.asarray(cts), np.asarray(cvals)
    S = tsn.shape[1]
    dense_ts = np.full((S, tsn.shape[0]), 2**60, np.int64)
    dense_v = np.full((S, tsn.shape[0]), np.nan)
    for s in range(S):
        keep = np.isfinite(vn[:, s])
        k = keep.sum()
        dense_ts[s, :k] = tsn[keep, s]
        dense_v[s, :k] = vn[keep, s]
    return dense_ts, dense_v


class TestGridDenseOnlyOps:
    """changes/resets/irate/idelta: consecutive-sample adjacency ops —
    grid-served only under the dense contract; exact vs windows."""

    @pytest.mark.parametrize("op,wfn", [
        ("changes", "changes_over_time"), ("resets", "resets_over_time"),
        ("irate", "irate"), ("idelta", "idelta")])
    def test_dense_matches_windows(self, op, wfn):
        cts, cvals = _dense_data(reset_frac=0.1)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op,
                      dense=True)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        # live lanes are fully dense: compaction is the identity there
        tsn, vn = np.asarray(cts), np.asarray(cvals)
        fn = getattr(windows, wfn)
        want = np.asarray(fn(jnp.asarray(tsn.T), jnp.asarray(vn.T), steps,
                             jnp.asarray(K * STEP, jnp.int64))).T
        live = np.isfinite(vn).any(axis=0)
        got_l, want_l = got[:, live], want[:, live]
        assert (np.isfinite(got_l) == np.isfinite(want_l)).all(), op
        both = np.isfinite(got_l)
        np.testing.assert_allclose(got_l[both], want_l[both], rtol=1e-9)
        # empty lanes come back NaN
        assert np.isnan(got[:, ~live]).all()

    @pytest.mark.parametrize("gap_frac,dense", [(0.0, True), (0.15, False)])
    def test_delta_matches_windows(self, gap_frac, dense):
        ts, vals = _aligned_data(gap_frac=gap_frac)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="delta", dense=dense)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        want = np.asarray(windows.delta_fn(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64))).T
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-9)

    @pytest.mark.parametrize("gap_frac,dense", [(0.0, True), (0.15, False)])
    def test_timestamp_matches_windows(self, gap_frac, dense):
        ts, vals = _aligned_data(gap_frac=gap_frac)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="timestamp", dense=dense)
        cts, cvals = _clip(ts, vals)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        want = np.asarray(windows.timestamp_fn(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64))).T
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        # the kernel emits WINDOW-relative seconds (f32-exact); the
        # serving layer re-bases in f64 — re-base here the same way
        abs_got = got + (np.asarray(steps, dtype=np.float64)
                         / 1000.0)[:, None]
        np.testing.assert_allclose(abs_got[both], want[both], rtol=1e-12)

    @pytest.mark.parametrize("phi", [0.0, 0.25, 0.5, 0.9, 1.0])
    def test_quantile_matches_windows(self, phi):
        from filodb_tpu.query import rangefns as rf
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="quantile", dense=True, farg=phi)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.quantile_over_time(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64), wmax, phi)).T
        live = np.isfinite(np.asarray(cvals)).any(axis=0)
        assert (np.isfinite(got) == np.isfinite(want))[:, live].all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-9)

    def test_mad_matches_windows(self):
        from filodb_tpu.query import rangefns as rf
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="mad", dense=True)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.mad_over_time(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64), wmax)).T
        live = np.isfinite(np.asarray(cvals)).any(axis=0)
        assert (np.isfinite(got) == np.isfinite(want))[:, live].all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-9)

    def test_holt_winters_matches_windows(self):
        from filodb_tpu.query import rangefns as rf
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="holt_winters", dense=True, farg=0.3, farg2=0.1)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        dense_ts, dense_v = _compact(cts, cvals)
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.holt_winters(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64), wmax, 0.3, 0.1)).T
        live = np.isfinite(np.asarray(cvals)).any(axis=0)
        assert (np.isfinite(got) == np.isfinite(want))[:, live].all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-9)

    @pytest.mark.parametrize("op", ["quantile", "mad", "holt_winters"])
    def test_sort_ops_pallas_interpret(self, op):
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op,
                      dense=True, farg=0.9, farg2=0.1)
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(steps[0])), q, lanes=128,
                                   interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all(), op
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=1e-5)

    @pytest.mark.parametrize("op", ["changes", "resets", "irate", "idelta",
                                    "quantile", "mad", "holt_winters"])
    def test_general_mode_rejected(self, op):
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op,
                      dense=False)
        with pytest.raises(ValueError, match="dense"):
            rate_grid_ref(cts, cvals.astype(jnp.float64), int(steps[0]), q)

    @pytest.mark.parametrize("op", ["changes", "resets", "irate", "idelta",
                                    "stddev", "stdvar"])
    def test_pallas_interpret(self, op):
        cts, cvals = _dense_data(reset_frac=0.1)
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP, op=op,
                      dense=(op not in ("stddev", "stdvar")))
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(steps[0])), q, lanes=128,
                                   interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all(), op
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=3e-4,
                                   atol=1e-5)


class TestGridDense:
    """The dense fast path (GridQuery.dense) vs the general kernel on
    contract-conforming data: results must be identical — the dense
    kernel is an algebraic simplification, not an approximation."""

    ALL_OPS = ["rate", "increase", "sum", "count", "avg", "min", "max",
               "last"]

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_ref_dense_equals_general(self, op):
        cts, cvals = _dense_data()
        steps = _steps()
        qd = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                       op=op, is_rate=(op == "rate"), dense=True)
        qg = qd._replace(dense=False)
        dense = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                         cvals.astype(jnp.float64),
                                         int(steps[0]), qd))
        general = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                           cvals.astype(jnp.float64),
                                           int(steps[0]), qg))
        assert (np.isfinite(dense) == np.isfinite(general)).all(), op
        both = np.isfinite(dense)
        np.testing.assert_allclose(dense[both], general[both], rtol=1e-12)

    @pytest.mark.parametrize("op", ALL_OPS)
    def test_pallas_interpret_dense(self, op):
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op=op, is_rate=(op == "rate"), dense=True)
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(steps[0])), q, lanes=128,
                                   interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all(), op
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=5e-5,
                                   atol=1e-6)

    def test_grouped_dense(self):
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      dense=True)
        s, c = rate_grid_grouped(cts.astype(jnp.int32),
                                 cvals.astype(jnp.float32),
                                 int(steps[0]), q, group_lanes=16,
                                 interpret=True)
        r = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                     cvals.astype(jnp.float32),
                                     int(steps[0]), q._replace(dense=False)))
        s, c = np.asarray(s), np.asarray(c)
        for g in range(8):
            rg = r[:, g * 16:(g + 1) * 16]
            ok = np.isfinite(rg)
            np.testing.assert_allclose(s[g], np.where(ok, rg, 0).sum(axis=1),
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_array_equal(c[g], ok.sum(axis=1))

    def test_strided_matches_unstrided_subsample(self):
        """stride=r output == every r-th step of the stride-1 output —
        the coarser dashboard step is a pure subsample of the windows."""
        cts, cvals = _dense_data()
        for r in (2, 3):
            full_steps = _steps()
            sub_steps = np.asarray(full_steps)[::r]
            q1 = GridQuery(nsteps=len(full_steps), kbuckets=K, gstep_ms=STEP)
            qr = GridQuery(nsteps=len(sub_steps), kbuckets=K, gstep_ms=STEP,
                           stride=r)
            full = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float64),
                                            int(full_steps[0]), q1))
            strided = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float64),
                                               int(sub_steps[0]), qr))
            want = full[::r]
            assert strided.shape == want.shape
            both = np.isfinite(want)
            assert (np.isfinite(strided) == both).all(), r
            np.testing.assert_allclose(strided[both], want[both], rtol=1e-12)

    @pytest.mark.parametrize("op", ["rate", "sum", "min", "last"])
    @pytest.mark.parametrize("dense", [False, True])
    def test_strided_pallas_interpret(self, op, dense):
        cts, cvals = _dense_data() if dense \
            else _clip(*_aligned_data(n_series=128))
        r = 2
        sub_steps = np.asarray(_steps())[::r]
        q = GridQuery(nsteps=len(sub_steps), kbuckets=K, gstep_ms=STEP,
                      op=op, is_rate=(op == "rate"), dense=dense, stride=r)
        ref = np.asarray(rate_grid_ref(cts.astype(jnp.int32),
                                       cvals.astype(jnp.float32),
                                       int(sub_steps[0]), q))
        pal = np.asarray(rate_grid(cts.astype(jnp.int32),
                                   cvals.astype(jnp.float32),
                                   jnp.int32(int(sub_steps[0])), q,
                                   lanes=128, interpret=True))
        assert (np.isfinite(ref) == np.isfinite(pal)).all(), (op, dense)
        both = np.isfinite(ref)
        np.testing.assert_allclose(pal[both], ref[both], rtol=5e-5,
                                   atol=1e-6)

    def test_supports_grid_stride_and_row_caps(self, monkeypatch):
        assert supports_grid(300_000, 120_000, 60_000)    # step = 2 buckets
        assert not supports_grid(300_000, 90_000, 60_000)  # non-multiple
        # the row cap is a VMEM tile bound: TPU backends only
        import filodb_tpu.ops.grid as gridmod
        monkeypatch.setattr(gridmod.jax, "default_backend", lambda: "tpu")
        assert supports_grid(300_000, 60_000, 60_000, nsteps=1000)
        assert not supports_grid(300_000, 600_000, 60_000, nsteps=1000)
        monkeypatch.setattr(gridmod.jax, "default_backend", lambda: "cpu")
        assert supports_grid(300_000, 600_000, 60_000, nsteps=1000)
        # the span cap holds on ANY backend: a 1h step over 1s cadence
        # would stage >1M buckets of blocks per query
        assert not supports_grid(60_000, 3_600_000, 1_000, nsteps=336)

    def test_counter_reset_still_corrected(self):
        """Dense data with a reset mid-range: the dense correction must
        fire exactly like the general one."""
        n = 16
        base = (np.arange(B, dtype=np.int64) * STEP + T0 - STEP + 1)[:, None]
        ts = base + 10_000 + np.zeros((B, n), np.int64)
        vals = np.cumsum(np.full((B, n), 7.0), axis=0)
        vals[20:, :] -= vals[20, 0] - 1.0          # reset at row 20
        cts, cvals = _clip(jnp.asarray(ts), jnp.asarray(vals))
        steps = _steps()
        qd = GridQuery(len(steps), K, STEP, True, dense=True)
        dense = np.asarray(rate_grid_ref(cts, cvals, int(steps[0]), qd))
        general = np.asarray(rate_grid_ref(cts, cvals, int(steps[0]),
                                           qd._replace(dense=False)))
        both = np.isfinite(dense) & np.isfinite(general)
        assert both.any()
        np.testing.assert_allclose(dense[both], general[both], rtol=1e-12)
        assert (np.isfinite(dense) == np.isfinite(general)).all()


class TestAdviceParityFixes:
    """Round-2 ADVICE findings: out-of-range quantile phi and idelta
    zero-interval semantics must agree across grid and windows paths."""

    @pytest.mark.parametrize("phi", [1.5, -0.5])
    def test_quantile_out_of_range_phi(self, phi):
        from filodb_tpu.query import rangefns as rf
        cts, cvals = _dense_data()
        steps = _steps()
        q = GridQuery(nsteps=len(steps), kbuckets=K, gstep_ms=STEP,
                      op="quantile", dense=True, farg=phi)
        got = np.asarray(rate_grid_ref(cts.astype(jnp.int64),
                                       cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        expect = np.inf if phi > 1.0 else -np.inf
        live = np.isfinite(np.asarray(cvals)).any(axis=0)
        assert (got[:, live] == expect).all()
        assert np.isnan(got[:, ~live]).all()
        # windows fallback: same ±Inf on live windows, NaN on empty
        tsn, vn = np.asarray(cts), np.asarray(cvals)
        S = tsn.shape[1]
        dense_ts = np.full((S, tsn.shape[0]), 2**60, np.int64)
        dense_v = np.full((S, tsn.shape[0]), np.nan)
        for s in range(S):
            fin = np.isfinite(vn[:, s])
            dense_ts[s, :fin.sum()] = tsn[fin, s]
            dense_v[s, :fin.sum()] = vn[fin, s]
        wmax = rf.bucket_wmax(dense_ts, np.asarray(steps), K * STEP)
        want = np.asarray(windows.quantile_over_time(
            jnp.asarray(dense_ts), jnp.asarray(dense_v), steps,
            jnp.asarray(K * STEP, jnp.int64), wmax, phi)).T
        assert (want[:, live] == expect).all()
        assert np.isnan(want[:, ~live]).all()

    def test_idelta_zero_interval_dropped(self):
        """Two adjacent rows with IDENTICAL timestamps (possible on the
        public rate_grid_ref API): idelta must drop the pair like irate
        does, matching the reference's shared instant-pair guard."""
        n = 8
        base = (np.arange(B, dtype=np.int64) * STEP + T0 - STEP + 1)[:, None]
        ts = base + 10_000 + np.zeros((B, n), np.int64)
        ts[-1, :] = ts[-2, :]                      # dt == 0 at the pair
        vals = np.cumsum(np.full((B, n), 3.0), axis=0)
        cts, cvals = _clip(jnp.asarray(ts), jnp.asarray(vals))
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, op="idelta", dense=True)
        out = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float64),
                                       int(steps[0]), q))
        # the final window's instant pair has dt==0 -> NaN there
        assert np.isnan(out[-1, :]).all()
        assert np.isfinite(out[:-1, :]).all()


def _phase_data(n_series=128, n_empty=16, seed=11, reset_frac=0.08):
    """Dense data with UNIFORM per-lane phase: every live lane scraped at
    a constant offset within its bucket (the reference producer's shape —
    TestTimeseriesProducer.scala:128 emits exact-cadence timestamps)."""
    rng = np.random.default_rng(seed)
    phase = rng.integers(1, STEP, n_series).astype(np.int64)
    base = (np.arange(B, dtype=np.int64) * STEP + T0 - STEP)[:, None]
    ts = base + phase[None, :]
    incr = rng.random((B, n_series)) * 10.0
    vals = np.cumsum(incr, axis=0)
    resets = rng.random((B, n_series)) < reset_frac
    for s in range(n_series):
        for c in np.where(resets[:, s])[0]:
            vals[c:, s] -= vals[c, s] * 0.9
    vals[:, n_series - n_empty:] = np.nan
    cts, cvals = _clip(jnp.asarray(ts), jnp.asarray(vals))
    return cts, cvals, jnp.asarray(phase, jnp.int32)


class TestPhaseMode:
    """Uniform-phase kernels: the ts plane is replaced by one per-lane
    phase row; results must match the ts-streaming dense path exactly."""

    @pytest.mark.parametrize("op", ["rate", "increase", "delta"])
    def test_ref_phase_matches_ref_ts(self, op):
        from filodb_tpu.ops.grid import rate_grid_ref
        cts, cvals, phase = _phase_data()
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, op == "rate", op=op, dense=True)
        want = np.asarray(rate_grid_ref(cts, cvals.astype(jnp.float64),
                                        int(steps[0]), q))
        got = np.asarray(rate_grid_ref(None, cvals.astype(jnp.float64),
                                       int(steps[0]), q, phase=phase))
        assert (np.isfinite(got) == np.isfinite(want)).all()
        both = np.isfinite(got) & np.isfinite(want)
        np.testing.assert_allclose(got[both], want[both], rtol=1e-12)

    @pytest.mark.parametrize("op", ["rate", "increase", "delta"])
    def test_pallas_interpret_phase(self, op):
        from filodb_tpu.ops.grid import rate_grid, rate_grid_ref
        cts, cvals, phase = _phase_data()
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, op == "rate", op=op, dense=True)
        want = np.asarray(rate_grid_ref(None, cvals, int(steps[0]), q,
                                        phase=phase))
        got = np.asarray(rate_grid(None, cvals.astype(jnp.float32),
                                   int(steps[0]), q, lanes=128,
                                   interpret=True, phase=phase))
        both = np.isfinite(got) & np.isfinite(want)
        assert (np.isfinite(got) == np.isfinite(want)).all()
        np.testing.assert_allclose(got[both], want[both], rtol=2e-5)

    def test_pallas_interpret_phase_grouped(self):
        from filodb_tpu.ops.grid import rate_grid_grouped, rate_grid_ref
        cts, cvals, phase = _phase_data(n_series=128, n_empty=24)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, True, dense=True)
        # 8 groups x 16 lanes
        s, c = rate_grid_grouped(None, cvals.astype(jnp.float32),
                                 int(steps[0]), q, group_lanes=16,
                                 interpret=True, phase=phase)
        per = np.asarray(rate_grid_ref(None, cvals, int(steps[0]), q,
                                       phase=phase))   # [T, S]
        for g in range(8):
            seg = per[:, g*16:(g+1)*16]
            want_s = np.nansum(np.where(np.isfinite(seg), seg, 0.0), axis=1)
            want_c = np.isfinite(seg).sum(axis=1)
            np.testing.assert_allclose(np.asarray(s)[g], want_s, rtol=2e-5)
            np.testing.assert_array_equal(np.asarray(c)[g], want_c)

    def test_phase_mode_requires_dense(self):
        from filodb_tpu.ops.grid import _phase_mode
        q = GridQuery(10, K, STEP, True, dense=False)
        assert not _phase_mode(q, jnp.zeros(8, jnp.int32))
        assert _phase_mode(q._replace(dense=True), jnp.zeros(8, jnp.int32))
        assert not _phase_mode(q._replace(dense=True), None)
        assert not _phase_mode(q._replace(dense=True, op="sum"),
                               jnp.zeros(8, jnp.int32))

    def test_phase_strided_matches_subsample(self):
        from filodb_tpu.ops.grid import rate_grid_ref
        cts, cvals, phase = _phase_data()
        steps = _steps()
        ns_c = (len(steps) + 1) // 2
        qs = GridQuery(ns_c, K, STEP, True, dense=True, stride=2)
        q1 = GridQuery(len(steps), K, STEP, True, dense=True)
        got = np.asarray(rate_grid_ref(None, cvals, int(steps[0]), qs,
                                       phase=phase))
        fine = np.asarray(rate_grid_ref(None, cvals, int(steps[0]), q1,
                                        phase=phase))
        np.testing.assert_allclose(got, fine[::2], rtol=1e-12)


class TestTsFreeOps:
    """TS_FREE_OPS stream no ts plane: ts=None must work and match."""

    @pytest.mark.parametrize("op", ["sum", "min", "max", "count", "avg",
                                    "last", "stddev"])
    @pytest.mark.parametrize("dense", [True, False])
    def test_ts_none_matches(self, op, dense):
        from filodb_tpu.ops.grid import rate_grid, rate_grid_ref
        if dense:
            cts, cvals = _dense_data()
        else:
            ts, vals = _aligned_data()
            cts, cvals = _clip(ts, vals)
        steps = _steps()
        q = GridQuery(len(steps), K, STEP, op=op, dense=dense)
        want = np.asarray(rate_grid_ref(cts, cvals, int(steps[0]), q))
        got_ref = np.asarray(rate_grid_ref(None, cvals, int(steps[0]), q))
        np.testing.assert_array_equal(got_ref, want)
        got_pl = np.asarray(rate_grid(None, cvals.astype(jnp.float32),
                                      int(steps[0]), q, lanes=64,
                                      interpret=True))
        both = np.isfinite(got_pl) & np.isfinite(want)
        assert (np.isfinite(got_pl) == np.isfinite(want)).all()
        # stddev in f32 is ~1e-4 relative and near-zero variances see
        # absolute cancellation noise (see grid._masked_moments)
        np.testing.assert_allclose(got_pl[both], want[both],
                                   rtol=1e-3 if op == "stddev" else 1e-4,
                                   atol=1e-2 if op == "stddev" else 0)
