"""Fleet workload insights (ISSUE 19).

The load-bearing assertions are the generative merge-algebra sweeps:
``merge_snapshots`` / ``merge_slo`` are commutative, associative, and
invariant to HOW one query stream was partitioned across nodes — any
split of the same events merges to the bit-identical fleet view.  Plus
ledger/SLO unit behavior, ``plan_keys`` fallbacks, the result-cache
bypass counter per reason label, and trace head-sampling."""

import random

import pytest

from filodb_tpu.insights import ledger as il
from filodb_tpu.insights.ledger import (LATENCY_BUCKETS_MS, WorkloadLedger,
                                        merge_snapshots, plan_keys)
from filodb_tpu.insights.slo import (SloObjective, SloTracker, merge_slo)
from filodb_tpu.promql.parser import (query_range_to_logical_plan,
                                      query_to_logical_plan)
from filodb_tpu.utils.forensics import TraceStore
from filodb_tpu.utils.observability import resultcache_metrics, slo_metrics

BASE = 1_700_000_000_000


# ---------------------------------------------------------------------------
# workload ledger
# ---------------------------------------------------------------------------


def _mk_ledger(**kw):
    led = WorkloadLedger(node=kw.pop("node", "n0"), **kw)
    led.started_at_ms = BASE     # deterministic snapshots across ledgers
    return led


class TestWorkloadLedger:
    def test_note_accumulates_integer_fields(self):
        led = _mk_ledger()
        led.note("fp1", query="up", dataset="prom", tenant="acme",
                 latency_s=0.012, samples=100, resultcache="hit",
                 device_programs=2, device_s=0.003, hbm_bytes=4096,
                 batch_key="bk")
        led.note("fp1", query="up", dataset="prom", tenant="acme",
                 latency_s=0.050, error=True, samples=50,
                 resultcache="miss", batch_key="bk")
        snap = led.snapshot()
        e = snap["fingerprints"]["fp1"]
        assert e["count"] == 2 and e["errors"] == 1
        assert e["latency_us"] == 12000 + 50000
        assert e["samples"] == 150
        assert e["rc_hit"] == 1 and e["rc_miss"] == 1
        assert e["device_programs"] == 2 and e["device_us"] == 3000
        assert e["hbm_bytes"] == 4096
        assert e["tenants"] == {"acme": 2}
        assert sum(e["lat_buckets"]) == 2
        assert snap["tenants"]["acme"]["count"] == 2
        assert snap["tenants"]["acme"]["errors"] == 1
        # every accumulator is an int — the merge-exactness contract
        for k, v in e.items():
            if isinstance(v, (dict, list, str)):
                continue
            assert isinstance(v, int), (k, v)

    def test_shed_reasons_fold(self):
        led = _mk_ledger()
        led.note("fp", shed_reason="overload")
        led.note("fp", shed_reason="overload")
        led.note("fp", shed_reason="deadline_exceeded")
        e = led.snapshot()["fingerprints"]["fp"]
        assert e["sheds"] == {"overload": 2, "deadline_exceeded": 1}

    def test_lru_eviction_reports_dropped(self):
        led = _mk_ledger(max_entries=2)
        assert led.note("a") == 0
        assert led.note("b") == 0
        assert led.note("c") == 1          # evicts "a"
        snap = led.snapshot()
        assert set(snap["fingerprints"]) == {"b", "c"}
        assert snap["dropped"] == 1
        # touching "b" refreshes recency: "c" is the next victim
        led.note("b")
        led.note("d")
        assert set(led.snapshot()["fingerprints"]) == {"b", "d"}

    def test_disabled_ledger_is_inert(self):
        led = _mk_ledger(enabled=False)
        assert led.note("fp") == 0
        assert led.note_arrival("bk") == 1
        assert led.snapshot()["fingerprints"] == {}

    def test_co_arrival_window(self):
        led = _mk_ledger(co_window_ms=10_000)
        assert led.note_arrival("bk") == 1
        assert led.note_arrival("bk") == 2
        assert led.note_arrival("other") == 1
        row = led.snapshot()["batch"]["bk"]
        assert row["arrivals"] == 2
        assert row["co_arrived"] == 1      # only the 2nd saw company
        assert row["peak"] == 2

    def test_co_arrival_window_expires(self):
        led = _mk_ledger(co_window_ms=0.0)
        assert led.note_arrival("bk") == 1
        assert led.note_arrival("bk") == 1  # horizon == now: alone again

    def test_snapshot_is_deep_copied(self):
        led = _mk_ledger()
        led.note("fp", tenant="t")
        s1 = led.snapshot()
        s1["fingerprints"]["fp"]["count"] = 999
        s1["fingerprints"]["fp"]["tenants"]["t"] = 999
        assert led.snapshot()["fingerprints"]["fp"]["count"] == 1
        assert led.snapshot()["fingerprints"]["fp"]["tenants"]["t"] == 1

    def test_quiesced_snapshots_bit_identical(self):
        led = _mk_ledger()
        for i in range(10):
            led.note(f"fp{i % 3}", latency_s=0.001 * i, samples=i)
            led.note_arrival("bk")
        assert led.snapshot() == led.snapshot()

    def test_quantiles_land_in_bucket(self):
        led = _mk_ledger()
        for _ in range(100):
            led.note("fp", latency_s=0.007)   # 7ms -> (5, 10] bucket
        e = led.snapshot()["fingerprints"]["fp"]
        for q in (0.5, 0.95, 0.99):
            assert 5.0 < il._quantile_ms(e, q) <= 10.0

    def test_view_top_k_and_sort(self):
        led = _mk_ledger()
        for _ in range(5):
            led.note("hot", query="hot_q", samples=10)
        led.note("cold", query="cold_q", samples=1_000_000)
        v = il.view(led.snapshot(), top=1, sort="count")
        assert v["fingerprints"] == 2
        assert len(v["top"]) == 1
        assert v["top"][0]["fingerprint"] == "hot"
        v = il.view(led.snapshot(), top=1, sort="cost")
        assert v["top"][0]["fingerprint"] == "cold"
        # unknown sort falls back to cost rather than exploding
        assert il.view(led.snapshot(), sort="nope")["sort"] == "cost"

    def test_view_batching_headroom(self):
        led = _mk_ledger(co_window_ms=10_000)
        for _ in range(3):
            led.note_arrival("bk")
        v = il.view(led.snapshot())
        assert v["batching"]["headroom"] == 3
        assert v["batching"]["keys"][0]["batch_key"] == "bk"


# ---------------------------------------------------------------------------
# plan_keys
# ---------------------------------------------------------------------------


class TestPlanKeys:
    def test_range_query_uses_cache_fingerprint(self):
        plan = query_range_to_logical_plan(
            "rate(http_requests_total[1m])", BASE, 15_000, BASE + 300_000)
        fp, bk = plan_keys("prom", plan, "rate(http_requests_total[1m])")
        assert not fp.startswith("q:")
        assert bk.startswith("prom|")
        assert "res=15000" in bk and "steps=21" in bk

    def test_instant_query_keys(self):
        plan = query_to_logical_plan("up", BASE)
        fp, bk = plan_keys("prom", plan, "up")
        assert fp and "steps=1" in bk       # instant = one grid step

    def test_non_periodic_plan_falls_back(self):
        fp, bk = plan_keys("prom", object(), "whatever")
        assert fp == "q:object:whatever"
        assert bk == "prom|object|res=0|steps=0"

    def test_unfingerprintable_shape_falls_back(self):
        q = "up offset 5m"
        plan = query_range_to_logical_plan(q, BASE, 15_000, BASE + 60_000)
        fp, _ = plan_keys("prom", plan, q)
        assert fp.startswith("q:")

    def test_same_shape_same_batch_key(self):
        q1 = 'up{job="a"}'
        q2 = 'up{job="b"}'
        p1 = query_range_to_logical_plan(q1, BASE, 15_000, BASE + 300_000)
        p2 = query_range_to_logical_plan(q2, BASE, 15_000, BASE + 300_000)
        fp1, bk1 = plan_keys("prom", p1, q1)
        fp2, bk2 = plan_keys("prom", p2, q2)
        assert fp1 != fp2                   # different queries
        assert bk1 == bk2                   # but batchable together


# ---------------------------------------------------------------------------
# merge algebra (generative)
# ---------------------------------------------------------------------------


def _random_events(rng, n):
    tenants = ["", "acme", "globex"]
    rcs = ["", "hit", "partial", "miss"]
    sheds = ["", "overload", "deadline_exceeded"]
    out = []
    for _ in range(n):
        out.append(dict(
            fingerprint=f"fp{rng.randrange(6)}",
            query=f"q{rng.randrange(6)}", dataset="prom",
            tenant=rng.choice(tenants),
            latency_s=rng.random() * 2.0,
            error=rng.random() < 0.1,
            samples=rng.randrange(10_000),
            resultcache=rng.choice(rcs),
            device_programs=rng.randrange(4),
            device_s=rng.random() * 0.01,
            hbm_bytes=rng.randrange(1 << 20),
            shed_reason=rng.choice(sheds),
            batch_key=f"bk{rng.randrange(3)}"))
    return out


def _ledger_for(events, node="n"):
    led = _mk_ledger(node=node)
    for ev in events:
        led.note(ev["fingerprint"], **{k: v for k, v in ev.items()
                                       if k != "fingerprint"})
    return led


def _canon(merged):
    """Strip the partition-dependent identity fields; everything else
    must be bit-identical across partitionings."""
    out = dict(merged)
    out.pop("nodes", None)
    out.pop("node", None)
    out.pop("started_at_ms", None)
    return out


class TestMergeAlgebra:
    @pytest.mark.parametrize("seed", range(8))
    def test_partition_invariant(self, seed):
        rng = random.Random(seed)
        events = _random_events(rng, 200)
        whole = _ledger_for(events, node="solo").snapshot()
        nparts = rng.randrange(2, 5)
        parts = [[] for _ in range(nparts)]
        for ev in events:
            parts[rng.randrange(nparts)].append(ev)
        snaps = [_ledger_for(p, node=f"n{i}").snapshot()
                 for i, p in enumerate(parts)]
        merged = merge_snapshots(snaps)
        assert _canon(merged) == _canon(merge_snapshots([whole]))

    @pytest.mark.parametrize("seed", range(4))
    def test_commutative(self, seed):
        rng = random.Random(1000 + seed)
        snaps = [_ledger_for(_random_events(rng, 60), node=f"n{i}")
                 .snapshot() for i in range(3)]
        ref = merge_snapshots(snaps)
        perm = list(snaps)
        rng.shuffle(perm)
        assert merge_snapshots(perm) == ref

    @pytest.mark.parametrize("seed", range(4))
    def test_associative(self, seed):
        rng = random.Random(2000 + seed)
        a, b, c = (_ledger_for(_random_events(rng, 60), node=f"n{i}")
                   .snapshot() for i in range(3))
        left = merge_snapshots([merge_snapshots([a, b]), c])
        right = merge_snapshots([a, merge_snapshots([b, c])])
        assert left == right == merge_snapshots([a, b, c])

    def test_mixed_bucket_bounds_refused(self):
        a = _mk_ledger(node="a").snapshot()
        b = _mk_ledger(node="b").snapshot()
        b["bounds_ms"] = [1, 2, 3]
        with pytest.raises(ValueError, match="bucket bounds"):
            merge_snapshots([a, b])

    def test_empty_merge(self):
        m = merge_snapshots([])
        assert m["fingerprints"] == {} and m["nodes"] == []
        assert m["bounds_ms"] == list(LATENCY_BUCKETS_MS)


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------


def _obj(**kw):
    kw.setdefault("name", "api")
    return SloObjective(**kw)


class TestSloTracker:
    def test_objective_matching(self):
        o = _obj(tenant="acme", priority="*")
        assert o.matches("acme", "interactive")
        assert not o.matches("globex", "interactive")
        assert _obj().matches("anyone", "anything")

    def test_from_config(self):
        o = SloObjective.from_config(
            {"name": "gold", "tenant": "acme",
             "latency-threshold-s": 0.25, "availability-target": 0.99}, 3)
        assert o.name == "gold" and o.tenant == "acme"
        assert o.latency_threshold_s == 0.25
        assert o.budget() == pytest.approx(0.01)
        assert SloObjective.from_config({}, 7).name == "slo-7"

    def test_budget_floor(self):
        assert _obj(target=1.0).budget() == pytest.approx(1e-9)

    def test_observe_and_burn(self):
        t = SloTracker([_obj(latency_threshold_s=0.1, target=0.9)],
                       node="n0", fast_window_s=60, slow_window_s=120)
        try:
            for _ in range(8):
                t.observe("acme", "interactive", 0.01)        # good
            t.observe("acme", "interactive", 0.5)             # slow: bad
            t.observe("acme", "interactive", 0.01, error=True)  # bad
            snap = t.snapshot()["objectives"]["api"]
            assert snap["total"] == 10 and snap["bad"] == 2
            # burn = (2/10) / 0.1 budget = 2.0, via the exported gauge
            g = slo_metrics()["fast_burn"].value(
                objective="api", tenant="*", node="n0")
            assert g == pytest.approx(2.0)
            assert t.burn("api", 60) == pytest.approx(2.0)
            assert t.burn("missing", 60) == 0.0
        finally:
            t.close()

    def test_no_traffic_burns_zero(self):
        t = SloTracker([_obj()], node="n1")
        try:
            assert t.burn("api", 300) == 0.0
        finally:
            t.close()

    def test_close_removes_gauge_rows(self):
        t = SloTracker([_obj()], node="n2")
        t.observe("x", "y", 10.0)
        assert slo_metrics()["fast_burn"].value(
            objective="api", tenant="*", node="n2") > 0
        t.close()
        assert slo_metrics()["fast_burn"].value(
            objective="api", tenant="*", node="n2") == 0.0

    def test_merge_slo_sums_and_flags_mismatch(self):
        a = {"node": "a", "objectives": {"api": {
            "tenant": "*", "priority": "*", "latency_threshold_ms": 100,
            "target_ppm": 999000, "total": 10, "bad": 2,
            "fast": {"total": 4, "bad": 1},
            "slow": {"total": 10, "bad": 2}}}}
        b = {"node": "b", "objectives": {"api": {
            "tenant": "*", "priority": "*", "latency_threshold_ms": 100,
            "target_ppm": 999000, "total": 5, "bad": 1,
            "fast": {"total": 2, "bad": 0},
            "slow": {"total": 5, "bad": 1}}}}
        m = merge_slo([a, b])
        o = m["objectives"]["api"]
        assert m["nodes"] == ["a", "b"]
        assert o["total"] == 15 and o["bad"] == 3
        assert o["fast"] == {"total": 6, "bad": 1}
        assert "latency_threshold_ms_mismatch" not in o
        # re-mergeable (associativity) + config drift surfaces
        c = {"node": "c", "objectives": {"api": {
            **b["objectives"]["api"], "latency_threshold_ms": 250,
            "fast": dict(b["objectives"]["api"]["fast"]),
            "slow": dict(b["objectives"]["api"]["slow"])}}}
        m2 = merge_slo([m, c])
        assert m2["nodes"] == ["a", "b", "c"]
        assert m2["objectives"]["api"]["total"] == 20
        assert m2["objectives"]["api"]["latency_threshold_ms_mismatch"]

    @pytest.mark.parametrize("seed", range(4))
    def test_merge_slo_partition_invariant(self, seed):
        rng = random.Random(seed)
        obj = _obj(latency_threshold_s=0.1)
        events = [(rng.random() * 0.3, rng.random() < 0.05)
                  for _ in range(100)]
        trackers = [SloTracker([obj], node=f"n{i}") for i in range(3)]
        solo = SloTracker([obj], node="solo")
        try:
            for lat, err in events:
                solo.observe("t", "p", lat, error=err)
                trackers[rng.randrange(3)].observe("t", "p", lat,
                                                   error=err)
            merged = merge_slo([t.snapshot() for t in trackers])
            want = merge_slo([solo.snapshot()])
            merged.pop("nodes"), want.pop("nodes")
            assert merged == want
        finally:
            solo.close()
            for t in trackers:
                t.close()


# ---------------------------------------------------------------------------
# result-cache bypass counter (satellite: one test per reason label)
# ---------------------------------------------------------------------------


def _bypass(reason):
    return resultcache_metrics()["bypass"].value(dataset="prom",
                                                 reason=reason)


@pytest.fixture
def rc_harness():
    from tests.test_resultcache import _Harness
    h = _Harness()
    h.ingest("up", [({"job": "a"}, [1.0] * 30)],
             [BASE + i * 10_000 for i in range(30)])
    return h


class TestBypassCounter:
    def test_disabled(self, rc_harness):
        h = rc_harness
        h.cache.enabled = False
        before = _bypass("disabled")
        h.eval_range(h.cached, "up", BASE, 10_000, BASE + 100_000)
        assert _bypass("disabled") == before + 1
        # metadata/raw plans are NOT cache traffic: no extra count
        h.eval_instant(h.cached, "up", BASE + 100_000)
        assert _bypass("disabled") == before + 2

    def test_unfingerprintable(self, rc_harness):
        h = rc_harness
        before = _bypass("unfingerprintable")
        h.eval_range(h.cached, "up offset 5m",
                     BASE + 400_000, 10_000, BASE + 500_000)
        assert _bypass("unfingerprintable") == before + 1

    def test_remote(self, rc_harness):
        h = rc_harness
        h.cached.inner.plan_is_local = lambda plan, qctx: False
        before = _bypass("remote")
        h.eval_range(h.cached, "up", BASE, 10_000, BASE + 100_000)
        assert _bypass("remote") == before + 1


# ---------------------------------------------------------------------------
# trace head-sampling (satellite)
# ---------------------------------------------------------------------------


class TestTraceHeadSampling:
    def test_rate_zero_drops_normal_traces(self):
        ts = TraceStore(slow_threshold_s=1.0, sample_rate=0.0)
        ts.note_complete("t1", 0.01, query="up", dataset="prom")
        assert ts.slowlog() == []

    def test_rate_one_retains_flagged(self):
        ts = TraceStore(slow_threshold_s=1.0, sample_rate=1.0)
        ts.note_complete("t1", 0.01, query="up", dataset="prom")
        log = ts.slowlog()
        assert len(log) == 1
        assert log[0]["sampled"] is True
        assert log[0]["trace_id"] == "t1"

    def test_slow_traces_retained_regardless(self):
        ts = TraceStore(slow_threshold_s=0.001, sample_rate=0.0)
        ts.note_complete("t2", 5.0, query="up", dataset="prom")
        log = ts.slowlog()
        assert len(log) == 1
        assert log[0]["sampled"] is False

    def test_fractional_rate_statistics(self):
        random.seed(42)
        ts = TraceStore(slow_threshold_s=1.0, slowlog_size=2048,
                        sample_rate=0.5)
        for i in range(400):
            ts.note_complete(f"t{i}", 0.001)
        kept = len(ts.slowlog())
        assert 120 < kept < 280          # ~200 expected, wide tolerance
