"""Offline jobs, cluster bootstrap, thread-discipline assertions.

Mirrors the reference's spark-jobs specs (ChunkCopier/cardbuster/
DSIndexJob), akka-bootstrapper specs, and the FiloSchedulers assertion
behavior."""

import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.bootstrap import (ClusterBootstrap,
                                              DnsSeedDiscovery,
                                              ExplicitListSeedDiscovery)
from filodb_tpu.coordinator.cluster import FailureDetector, ShardManager
from filodb_tpu.coordinator.node import IngestionCoordinator
from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.downsample.dsstore import ds_dataset_name
from filodb_tpu.ingest.stream import ListStreamFactory
from filodb_tpu.jobs import (ChunkCopier, DSIndexJob,
                             PerShardCardinalityBuster)
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.persistence import DiskColumnStore
from filodb_tpu.utils import schedulers

BASE = 1_700_000_000_000


def _seed_store(tmp_path, n_series=6, name="c.db"):
    disk = DiskColumnStore(str(tmp_path / name))
    ms = TimeSeriesMemStore(disk)
    ms.setup("prom", DEFAULT_SCHEMAS, 0)
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    for i in range(n_series):
        tags = {"__name__": "job_metric", "instance": f"i{i}",
                "group": "even" if i % 2 == 0 else "odd",
                "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.cumsum(rng.integers(5_000, 15_000, 100))
        for t, v in zip(ts, rng.random(100)):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        ms.ingest("prom", 0, c, offset=off)
    ms.get_shard("prom", 0).flush_all(ingestion_time=500)
    return disk, ms


class TestChunkCopier:
    def test_copies_chunks_and_partkeys(self, tmp_path):
        src, _ = _seed_store(tmp_path)
        dst = DiskColumnStore(str(tmp_path / "target.db"))
        copier = ChunkCopier(src, dst, "prom")
        copied = copier.run([0], 0, 1000)
        assert copied[0] == src.num_chunks("prom", 0)
        assert dst.num_chunks("prom", 0) == src.num_chunks("prom", 0)
        # partkeys traveled too: target index recovery works
        ms2 = TimeSeriesMemStore(dst)
        ms2.setup("prom", DEFAULT_SCHEMAS, 0)
        assert ms2.recover_index("prom", 0) == 6
        res = ms2.get_shard("prom", 0).lookup_partitions(
            [ColumnFilter("_metric_", Equals("job_metric"))], 0, 2**62)
        tags_list, batch = ms2.get_shard("prom", 0).scan_batch(
            res.part_ids, 0, 2**62)
        assert len(tags_list) == 6

    def test_time_range_respected(self, tmp_path):
        src, _ = _seed_store(tmp_path)
        dst = DiskColumnStore(str(tmp_path / "t2.db"))
        copied = ChunkCopier(src, dst, "prom").run([0], 600, 1000)
        assert copied[0] == 0  # flushed at ingestion_time=500, outside range


class TestCardinalityBuster:
    def test_dry_run_counts_without_deleting(self, tmp_path):
        disk, _ = _seed_store(tmp_path)
        buster = PerShardCardinalityBuster(disk, "prom")
        n = buster.bust_shard(0, [ColumnFilter("group", Equals("even"))],
                              dry_run=True)
        assert n == 3
        assert len(list(disk.scan_part_keys("prom", 0))) == 6

    def test_bust_deletes_matching(self, tmp_path):
        disk, _ = _seed_store(tmp_path)
        before_chunks = disk.num_chunks("prom", 0)
        buster = PerShardCardinalityBuster(disk, "prom")
        n = buster.bust_shard(0, [ColumnFilter("group", Equals("odd"))],
                              dry_run=False)
        assert n == 3
        remaining = [r for r in disk.scan_part_keys("prom", 0)]
        assert len(remaining) == 3
        assert disk.num_chunks("prom", 0) < before_chunks

    def test_regex_filters(self, tmp_path):
        disk, _ = _seed_store(tmp_path)
        buster = PerShardCardinalityBuster(disk, "prom")
        n = buster.bust_shard(0, [ColumnFilter("instance",
                                               EqualsRegex("i[01]"))])
        assert n == 2


class TestDSIndexJob:
    def test_migrates_partkeys_to_ds_datasets(self, tmp_path):
        disk, _ = _seed_store(tmp_path)
        job = DSIndexJob(disk, "prom", resolutions_ms=(60_000, 3_600_000))
        moved = job.run([0])
        assert moved[0] == 6
        for res_ms in (60_000, 3_600_000):
            name = ds_dataset_name("prom", res_ms)
            assert len(list(disk.scan_part_keys(name, 0))) == 6


class TestBootstrap:
    def test_explicit_seed_join(self):
        from filodb_tpu.http.server import FiloHttpServer
        # a live peer node exposing /__health
        mgr_peer = ShardManager()
        mgr_peer.setup_dataset("prom", 2, 1)
        mgr_peer.add_node("peer-1")
        peer_http = FiloHttpServer(shard_manager=mgr_peer)
        port = peer_http.start()
        try:
            mgr = ShardManager()
            fd = FailureDetector(mgr)
            boot = ClusterBootstrap(
                "node-0", fd,
                ExplicitListSeedDiscovery([f"http://127.0.0.1:{port}",
                                           "http://127.0.0.1:9"]))
            alive = boot.bootstrap()
            assert alive == ["peer-1"]
            assert set(fd.alive()) == {"node-0", "peer-1"}
            assert "peer-1" in boot.peers
        finally:
            peer_http.shutdown()

    def test_dns_discovery_localhost(self):
        d = DnsSeedDiscovery("localhost", 1234)
        endpoints = d.discover()
        assert any("127.0.0.1:1234" in e for e in endpoints)
        assert DnsSeedDiscovery("no-such-host-xyz.invalid", 1).discover() == []


class TestThreadAssertions:
    def test_assert_thread_name(self):
        schedulers.enable_assertions(True)
        try:
            with pytest.raises(schedulers.WrongThreadError):
                schedulers.assert_thread_name("ingest-")
            ok = []
            t = threading.Thread(
                target=lambda: ok.append(
                    schedulers.assert_thread_name("ingest-") or True),
                name="ingest-prom-0")
            t.start(); t.join()
            assert ok == [True]
        finally:
            schedulers.enable_assertions(False)

    def test_ingest_on_wrong_thread_trips(self):
        schedulers.enable_assertions(True)
        try:
            data = {0: []}
            b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
            b.add(BASE + 1, [1.0], {"__name__": "x", "_ws_": "w", "_ns_": "n"})
            data[0] = list(enumerate(b.containers()))
            ms = TimeSeriesMemStore()
            ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, ms,
                                      ListStreamFactory(data))
            ic.start_ingestion(0, blocking=True)  # adopts the ingest name
            sh = ms.get_shard("prom", 0)
            assert sh.stats.rows_ingested == 1
            # direct ingest from this (wrong) thread trips the tripwire
            with pytest.raises(schedulers.WrongThreadError):
                sh.ingest_container(b.containers()[0] if b.containers()
                                    else data[0][0][1], offset=99)
        finally:
            schedulers.enable_assertions(False)

    def test_threaded_ingestion_passes_assertions(self):
        schedulers.enable_assertions(True)
        try:
            b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
            for i in range(10):
                b.add(BASE + 1000 * (i + 1), [float(i)],
                      {"__name__": "y", "_ws_": "w", "_ns_": "n"})
            data = {0: list(enumerate(b.containers()))}
            ms = TimeSeriesMemStore()
            ic = IngestionCoordinator("n", "prom", DEFAULT_SCHEMAS, ms,
                                      ListStreamFactory(data))
            ic.start_ingestion(0)  # real named thread
            deadline = time.time() + 5
            while time.time() < deadline:
                try:
                    if ms.get_shard("prom", 0).stats.rows_ingested == 10:
                        break
                except Exception:
                    pass
                time.sleep(0.01)
            assert ms.get_shard("prom", 0).stats.rows_ingested == 10
            ic.stop_all()
        finally:
            schedulers.enable_assertions(False)


def test_copier_preserves_ingestion_times(tmp_path):
    """Regression: copied chunks keep their source ingestion times so
    incremental repair runs don't double-copy or miss ranges."""
    src_store = DiskColumnStore(str(tmp_path / "s.db"))
    ms = TimeSeriesMemStore(src_store)
    ms.setup("prom", DEFAULT_SCHEMAS, 0)
    rng = np.random.default_rng(0)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    tags = {"__name__": "m", "instance": "a", "_ws_": "w", "_ns_": "n"}
    ts = BASE + np.cumsum(rng.integers(5_000, 15_000, 100))
    for t, v in zip(ts, rng.random(100)):
        b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        ms.ingest("prom", 0, c, offset=off)
    ms.get_shard("prom", 0).flush_all(ingestion_time=100)
    # second batch at a later ingestion time
    b2 = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    t2 = int(ts[-1]) + np.cumsum(rng.integers(5_000, 15_000, 50))
    for t, v in zip(t2, rng.random(50)):
        b2.add(int(t), [float(v)], tags)
    for off, c in enumerate(b2.containers()):
        ms.ingest("prom", 0, c, offset=100 + off)
    ms.get_shard("prom", 0).flush_all(ingestion_time=200)

    dst = DiskColumnStore(str(tmp_path / "d.db"))
    ChunkCopier(src_store, dst, "prom").run([0], 0, 1000)
    # the target's ingestion-time scan distinguishes the two batches
    early = list(dst.chunksets_by_ingestion_time("prom", 0, 0, 150))
    late = list(dst.chunksets_by_ingestion_time("prom", 0, 151, 300))
    assert len(early) >= 1 and len(late) >= 1
    src_early = list(src_store.chunksets_by_ingestion_time("prom", 0, 0, 150))
    assert len(early) == len(src_early)


def test_buster_works_on_in_memory_store():
    from filodb_tpu.store.columnstore import InMemoryColumnStore
    from filodb_tpu.store.columnstore import PartKeyRecord
    from filodb_tpu.core.record import canonical_partkey
    store = InMemoryColumnStore()
    pk = canonical_partkey({"_metric_": "m", "kill": "yes"})
    store.write_part_keys("ds", 0, [PartKeyRecord(pk, 0, 1, 0)])
    buster = PerShardCardinalityBuster(store, "ds")
    assert buster.bust_shard(0, [ColumnFilter("kill", Equals("yes"))],
                             dry_run=False) == 1
    assert list(store.scan_part_keys("ds", 0)) == []
