"""Worker for the REAL 2-process distribution test: joins a
jax.distributed CPU runtime and runs the mesh serving program
(scan -> window -> psum over the shard axis) with its OWN shard's data;
the collective rides Gloo across actual OS processes — the CPU stand-in
for the reference's forked-JVM cluster specs (reference:
coordinator/src/multi-jvm/.../ClusterRecoverySpec.scala) and for ICI/DCN
collectives on a real TPU pod.

Usage: python mp_collective_worker.py <process_id> <coordinator_addr>
Prints "RESULT OK <checksum>" on success.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)       # exactly ONE local device/process

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def main() -> None:
    pid = int(sys.argv[1])
    addr = sys.argv[2]
    jax.distributed.initialize(coordinator_address=addr, num_processes=2,
                               process_id=pid)
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from filodb_tpu.core.chunk import build_batch
    from filodb_tpu.ops.windows import StepRange
    from filodb_tpu.parallel import mesh as meshmod
    from filodb_tpu.query import rangefns
    from filodb_tpu.query.logical import AggregationOperator as Agg
    from filodb_tpu.query.logical import RangeFunctionId as F

    assert len(jax.devices()) == 2, jax.devices()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 1),
                axis_names=("shard", "step"))
    key = meshmod._mesh_key(mesh)

    # BOTH processes generate BOTH shards deterministically (shared
    # seeds) so the oracle and static kernel config agree; each feeds
    # only ITS OWN shard into the mesh program.
    base = 1_700_000_000_000
    S, R = 4, 60
    batches = []
    for shard in range(2):
        rng = np.random.default_rng(100 + shard)
        ts = [base + np.arange(R, dtype=np.int64) * 10_000
              for _ in range(S)]
        vs = [np.cumsum(rng.random(R)) for _ in range(S)]
        batches.append(build_batch(ts, vs))
    srange = StepRange(base + 120_000, base + 500_000, 30_000)
    steps_np = np.asarray(srange.timestamps(np.int64))
    window_ms = 120_000

    ts_all = np.concatenate([b.timestamps for b in batches])   # [2S, R]
    vals_all = np.concatenate([b.values for b in batches])
    ids_all = np.zeros(2 * S, np.int32)                        # one group
    wmax = 0                                                   # prefix fn

    def dist(local_rows, global_rows, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(local_rows), global_rows)

    mine = batches[pid]
    d_ts = dist(mine.timestamps, (2 * S, R), P("shard", None))
    d_vals = dist(mine.values, (2 * S, R), P("shard", None))
    d_ids = dist(ids_all[pid * S:(pid + 1) * S], (2 * S,), P("shard"))
    d_steps = dist(steps_np, steps_np.shape, P("step"))

    prog = meshmod._build_program(key, F.RATE, Agg.SUM, 1, window_ms,
                                  wmax, ())
    out = np.asarray(prog(d_ts, d_vals, d_ids, d_steps))       # [1, T]

    # oracle: host kernels over BOTH shards, summed
    expected = np.zeros(len(steps_np))
    for b in batches:
        stepped = np.asarray(rangefns.apply_range_function(
            b, srange, window_ms, F.RATE))
        expected += np.nansum(stepped, axis=0)
    fin = np.isfinite(out[0])
    assert fin.any(), "no finite outputs"
    assert np.allclose(out[0][fin], expected[fin], rtol=1e-9), \
        (out[0][:5], expected[:5])
    print(f"RESULT OK {float(np.nansum(out)):.6f}", flush=True)


if __name__ == "__main__":
    main()
