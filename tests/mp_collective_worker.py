"""Worker for the REAL 2-process distribution test: joins a
jax.distributed CPU runtime and runs the mesh serving program
(scan -> window -> psum over the shard axis) with its OWN shard's data;
the collective rides Gloo across actual OS processes — the CPU stand-in
for the reference's forked-JVM cluster specs (reference:
coordinator/src/multi-jvm/.../ClusterRecoverySpec.scala) and for ICI/DCN
collectives on a real TPU pod.

Usage: python mp_collective_worker.py <process_id> <coordinator_addr>
Prints "RESULT OK <checksum>" on success.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)       # exactly ONE local device/process

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
try:
    # jax >= 0.4.26 ships CPU cross-process collectives behind this
    # switch (default "none"): without it the compiled psum dies with
    # "Multiprocess computations aren't implemented on the CPU
    # backend".  Must be set BEFORE jax.distributed.initialize.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:  # noqa: BLE001 — older jax: collectives built in
    pass


def main() -> None:
    pid = int(sys.argv[1])
    addr = sys.argv[2]
    jax.distributed.initialize(coordinator_address=addr, num_processes=2,
                               process_id=pid)
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from filodb_tpu.core.chunk import build_batch
    from filodb_tpu.ops.windows import StepRange
    from filodb_tpu.parallel import mesh as meshmod
    from filodb_tpu.query import rangefns
    from filodb_tpu.query.logical import AggregationOperator as Agg
    from filodb_tpu.query.logical import RangeFunctionId as F

    assert len(jax.devices()) == 2, jax.devices()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 1),
                axis_names=("shard", "step"))
    key = meshmod._mesh_key(mesh)

    # BOTH processes generate BOTH shards deterministically (shared
    # seeds) so the oracle and static kernel config agree; each feeds
    # only ITS OWN shard into the mesh program.
    base = 1_700_000_000_000
    S, R = 4, 60
    batches = []
    for shard in range(2):
        rng = np.random.default_rng(100 + shard)
        ts = [base + np.arange(R, dtype=np.int64) * 10_000
              for _ in range(S)]
        vs = [np.cumsum(rng.random(R)) for _ in range(S)]
        batches.append(build_batch(ts, vs))
    srange = StepRange(base + 120_000, base + 500_000, 30_000)
    steps_np = np.asarray(srange.timestamps(np.int64))
    window_ms = 120_000

    ts_all = np.concatenate([b.timestamps for b in batches])   # [2S, R]
    vals_all = np.concatenate([b.values for b in batches])
    ids_all = np.zeros(2 * S, np.int32)                        # one group
    wmax = 0                                                   # prefix fn

    def dist(local_rows, global_rows, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(local_rows), global_rows)

    mine = batches[pid]
    d_ts = dist(mine.timestamps, (2 * S, R), P("shard", None))
    d_vals = dist(mine.values, (2 * S, R), P("shard", None))
    d_ids = dist(ids_all[pid * S:(pid + 1) * S], (2 * S,), P("shard"))
    d_steps = dist(steps_np, steps_np.shape, P("step"))

    prog = meshmod._build_program(key, F.RATE, Agg.SUM, 1, window_ms,
                                  wmax, ())
    out = np.asarray(prog(d_ts, d_vals, d_ids, d_steps))       # [1, T]

    # oracle: host kernels over BOTH shards, summed
    expected = np.zeros(len(steps_np))
    for b in batches:
        stepped = np.asarray(rangefns.apply_range_function(
            b, srange, window_ms, F.RATE))
        expected += np.nansum(stepped, axis=0)
    fin = np.isfinite(out[0])
    assert fin.any(), "no finite outputs"
    assert np.allclose(out[0][fin], expected[fin], rtol=1e-9), \
        (out[0][:5], expected[:5])
    print(f"RESULT OK {float(np.nansum(out)):.6f}", flush=True)

    # -- phase 2: the HBM-RESIDENT grid x mesh path across processes
    # (round-5 item 3).  Each process ingests only ITS shard into a real
    # TimeSeriesShard, pins the grid to its LOCAL device, and calls
    # serve_grid_mesh under the GLOBAL mesh: the per-process staged
    # pieces assemble into one global array and the psum rides the
    # cross-process collective — the flagship serving path, proven
    # across OS processes (reference: ClusterRecoverySpec.scala).
    from filodb_tpu.core.record import RecordBuilder
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel import meshgrid
    from filodb_tpu.parallel.mesh import MeshEngine

    engine = MeshEngine(mesh)
    local_dev = [d for d in jax.devices()
                 if d.process_index == jax.process_index()][0]
    ms = TimeSeriesMemStore()
    shard_store = ms.setup("prom", DEFAULT_SCHEMAS, pid)
    rng = np.random.default_rng(100 + pid)
    gb = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                       container_size=1 << 20)
    ts_row = base + np.arange(R, dtype=np.int64) * 10_000
    for i in range(S):
        tags = {"_metric_": "res", "inst": f"p{pid}-i{i}",
                "_ws_": "w", "_ns_": "n"}
        gb.add_series(ts_row, [np.cumsum(rng.random(R)).tolist()], tags)
    for off, c in enumerate(gb.containers()):
        shard_store.ingest_container(c, off)
    shard_store.pin_grid_device(local_dev)
    res = shard_store.lookup_partitions([], 0, 2**62)
    assert len(res.part_ids) == S
    plan = shard_store.mesh_grid_plan(
        res.part_ids, F.RATE, srange.start, srange.num_steps,
        srange.step, window_ms, np.zeros(S, np.int32))
    assert plan is not None, "shard not grid-eligible"
    before = dict(meshgrid.STATS)
    state = meshgrid.serve_grid_mesh(engine, [plan], 1, Agg.SUM)
    assert state is not None, "resident mesh path fell back"
    assert meshgrid.STATS["serves"] == before["serves"] + 1
    served = np.where(state["count"][0] > 0, state["sum"][0], np.nan)
    # oracle: both processes' generated data (shared seeds), host kernels
    expected_r = np.zeros(srange.num_steps)
    for p in range(2):
        rng2 = np.random.default_rng(100 + p)
        vs2 = [np.cumsum(rng2.random(R)) for _ in range(S)]
        b2 = build_batch([ts_row] * S, vs2)
        stepped = np.asarray(rangefns.apply_range_function(
            b2, srange, window_ms, F.RATE))
        expected_r += np.nansum(stepped, axis=0)
    finr = np.isfinite(served)
    assert finr.any(), "resident serve produced no finite samples"
    assert np.allclose(served[finr], expected_r[finr], rtol=1e-9), \
        (served[:5], expected_r[:5])
    # repeat query: assembled residents memoized on BOTH processes
    mid = dict(meshgrid.STATS)
    state2 = meshgrid.serve_grid_mesh(engine, [plan], 1, Agg.SUM)
    assert meshgrid.STATS["memo_hits"] == mid["memo_hits"] + 1
    assert np.allclose(np.nan_to_num(state2["sum"]),
                       np.nan_to_num(state["sum"]), rtol=1e-12)
    print(f"RESIDENT OK {float(np.nansum(served)):.6f} "
          f"serves={meshgrid.STATS['serves']}", flush=True)


if __name__ == "__main__":
    main()
