"""Data-integrity subsystem: checksums, tripwires, quarantine, faults.

End-to-end fault injection (filodb_tpu/integrity/faultinject.py): flip
bytes in chunks persisted in the sqlite ColumnStore and in staged
(in-memory frozen) chunk vectors, then prove the system gets LOUD and
CONTAINED — structured CorruptVectorError diagnosis with part-key +
chunk-id context, quarantine exclusion on re-query, partial-data
warnings on the query path, integrity counters — and that an
uncorrupted run trips none of it.
"""

import json
import urllib.request

import numpy as np
import pytest

from filodb_tpu import integrity, native
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.integrity import (QUARANTINE, CorruptVectorError,
                                  IntegrityInvariantError, chunk_crc,
                                  crc32c_py)
from filodb_tpu.integrity.faultinject import FaultInjector
from filodb_tpu.integrity.scan import verify_chunks
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore
from filodb_tpu.utils.observability import integrity_metrics

T0 = 1_700_000_000_000
STEP = 10_000
N_SERIES = 6
N_ROWS = 40
FILTERS = [ColumnFilter("_metric_", Equals("im"))]


@pytest.fixture(autouse=True)
def _clean_quarantine():
    QUARANTINE.clear()
    yield
    QUARANTINE.clear()


def _metric_totals() -> dict:
    return {k: m.total() for k, m in integrity_metrics().items()}


def _build_persisted(tmp_path, n_series=N_SERIES, n_rows=N_ROWS):
    """Ingest + flush a small gauge dataset into a disk store."""
    disk = DiskColumnStore(str(tmp_path / "chunks.db"))
    meta = DiskMetaStore(str(tmp_path / "meta.db"))
    ms = TimeSeriesMemStore(disk, meta)
    sh = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    ts = T0 + np.arange(n_rows, dtype=np.int64) * STEP
    rng = np.random.default_rng(1)
    for i in range(n_series):
        b.add_series(ts, [rng.random(n_rows) + i],
                     {"_metric_": "im", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"})
    for off, c in enumerate(b.containers()):
        sh.ingest_container(c, off)
    sh.flush_all(ingestion_time=1000)
    return disk, meta, ms, sh


def _cold_shard(disk, meta):
    """Fresh memstore over the same disk store: index-only partitions,
    every chunk pages in through the ODP read path."""
    cold = TimeSeriesMemStore(disk, meta)
    cold.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
    assert cold.recover_index("prom", 0) == N_SERIES
    return cold, cold.get_shard("prom", 0)


def _scan(shard):
    res = shard.lookup_partitions(FILTERS, 0, 2**62)
    return shard.scan_batch(res.part_ids, 0, 2**62)


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------


class TestCrc32c:
    def test_known_vector(self):
        # the standard CRC32C check value
        assert crc32c_py(b"123456789") == 0xE3069283

    def test_native_matches_python(self):
        if native.crc32c(b"") is None:
            pytest.skip("native library unavailable")
        for data in (b"", b"a", b"123456789", bytes(range(256)) * 33,
                     b"\x00" * 1000):
            assert native.crc32c(data) == crc32c_py(data), data[:16]

    def test_chunk_crc_never_zero(self):
        assert chunk_crc(b"") != 0  # 0 is the no-checksum marker


# ---------------------------------------------------------------------------
# Fault injector determinism
# ---------------------------------------------------------------------------


def test_faultinject_deterministic(tmp_path):
    disk, meta, ms, sh = _build_persisted(tmp_path)
    a = FaultInjector(42).corrupt_stored_chunk(disk, "prom", 0,
                                               mode="flip")
    # a second injector with the same seed picks the same victim
    b = FaultInjector(42)
    rows_pk, rows_cid = a
    assert (b.rng.random(), FaultInjector(42).rng.random()) == \
        (FaultInjector(42).rng.random(),) * 2
    assert FaultInjector(42).flip_byte(b"abcdef") == \
        FaultInjector(42).flip_byte(b"abcdef")
    assert isinstance(rows_pk, bytes) and isinstance(rows_cid, int)


# ---------------------------------------------------------------------------
# Checksum tripwire on ODP page-in (flipped byte in a stored chunk)
# ---------------------------------------------------------------------------


def test_checksum_flip_detected_and_quarantined(tmp_path):
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(3).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    before = _metric_totals()
    cold, shard = _cold_shard(disk, meta)
    tags, batch = _scan(shard)
    # the corrupt chunk is dropped at the store read: 5 of 6 series serve
    assert len(tags) == N_SERIES - 1
    assert QUARANTINE.is_quarantined(pk, cid)
    after = _metric_totals()
    assert after["checksum_failures"] - before["checksum_failures"] == 1
    assert after["chunks_verified"] > before["chunks_verified"]
    # quarantine detail carries the forensic context
    (item,) = [d for d in QUARANTINE.items() if d["chunk_id"] == cid]
    assert item["partkey"] == pk.hex()
    assert "checksum" in item["reason"]
    # re-query: exclusion via quarantine, NOT a second checksum failure
    tags2, _ = _scan(shard)
    assert len(tags2) == N_SERIES - 1
    assert _metric_totals()["checksum_failures"] == \
        after["checksum_failures"]


def test_corrupt_stored_crc_only(tmp_path):
    """Corrupting just the stored checksum (data intact) still trips the
    verify — the pair is the integrity unit, either half failing is
    loud."""
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(5).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="crc")
    cold, shard = _cold_shard(disk, meta)
    tags, _ = _scan(shard)
    assert len(tags) == N_SERIES - 1
    assert QUARANTINE.is_quarantined(pk, cid)


# ---------------------------------------------------------------------------
# Decode tripwire (corruption that EVADES the checksum)
# ---------------------------------------------------------------------------


def test_fixed_crc_truncation_hits_decode_tripwire(tmp_path):
    """fix_crc=True recomputes the checksum over the corrupted blob, so
    the CRC verify passes and the decode/framing tripwires must catch
    it — the defense-in-depth layer."""
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(11).corrupt_stored_chunk(
        disk, "prom", 0, mode="truncate", fix_crc=True)
    before = _metric_totals()
    cold, shard = _cold_shard(disk, meta)
    tags, batch = _scan(shard)
    # healthy series still serve; the corrupt chunk's rows never reach
    # the result (its series may still appear, with zero rows)
    assert len(tags) >= N_SERIES - 1
    assert int(np.asarray(batch.row_counts)[:len(tags)].sum()) == \
        (N_SERIES - 1) * N_ROWS
    assert QUARANTINE.is_quarantined(pk, cid)
    after = _metric_totals()
    assert after["checksum_failures"] == before["checksum_failures"]
    assert after["decode_failures"] > before["decode_failures"]
    # the bulk page-decode sentinel was counted, not silently discarded
    assert shard.stats.page_decode_corrupt >= 1


# ---------------------------------------------------------------------------
# Staging (in-memory frozen chunk) corruption: structured error
# ---------------------------------------------------------------------------


def test_staged_corruption_structured_error():
    ms = TimeSeriesMemStore()
    sh = ms.setup("prom", DEFAULT_SCHEMAS, 0)
    part = sh.create_partition("gauge", {"_metric_": "im", "inst": "i0",
                                         "_ws_": "w", "_ns_": "n"}, T0)
    for k in range(20):
        part.ingest(T0 + k * STEP, (float(k),))
    part.switch_buffers()
    cid = FaultInjector(7).corrupt_staged_chunk(part, chunk_index=0,
                                                mode="wire")
    with pytest.raises(CorruptVectorError) as ei:
        part._decoded_chunk(part.chunks[0])
    msg = str(ei.value)
    # the structured diagnosis: part-key AND chunk id in the message
    assert part.partkey.hex()[:32] in msg
    assert str(cid) in msg
    assert "partkey=" in msg and "chunk_id=" in msg
    assert ei.value.window is not None          # bounded hexdump window
    # the serving path skips it: quarantine + shard stats, not an error
    ts, vals = part.read_range(0, 2**62)
    assert len(ts) == 0
    assert QUARANTINE.is_quarantined(part.partkey, cid)
    assert sh.stats.chunks_corrupt >= 1
    assert sh.stats.chunks_quarantined == 1
    # a healthy sibling partition is untouched
    part2 = sh.create_partition("gauge", {"_metric_": "im", "inst": "i1",
                                          "_ws_": "w", "_ns_": "n"}, T0)
    for k in range(20):
        part2.ingest(T0 + k * STEP, (float(k),))
    part2.switch_buffers()
    ts2, _ = part2.read_range(0, 2**62)
    assert len(ts2) == 20


def test_staged_flip_caught_by_decode_or_serves_cleanly():
    """A random single-bit flip in an encoded vector either breaks the
    decode (-> structured error path) or decodes to different values —
    the checksum layer exists precisely because decode alone cannot
    catch everything.  Either way: NO crash, no silent 'missing data'
    page miss."""
    ms = TimeSeriesMemStore()
    sh = ms.setup("prom", DEFAULT_SCHEMAS, 0)
    part = sh.create_partition("gauge", {"_metric_": "im", "inst": "i0",
                                         "_ws_": "w", "_ns_": "n"}, T0)
    for k in range(50):
        part.ingest(T0 + k * STEP, (float(k) * 0.7,))
    part.switch_buffers()
    FaultInjector(13).corrupt_staged_chunk(part, chunk_index=0)
    ts, vals = part.read_range(0, 2**62)   # must not raise
    assert len(ts) in (0, 50)


# ---------------------------------------------------------------------------
# Eviction/reclaim invariants: fail the shard, never serve stale buffers
# ---------------------------------------------------------------------------


def test_paged_lru_invariant_check():
    from filodb_tpu.memstore.odp import _PagedPartitions
    p = _PagedPartitions(1 << 20)
    p.put(1, "x", 100)
    p.put(2, "y", 200)
    p.check_invariants()               # clean: no raise
    p._bytes += 7                      # simulate accounting drift
    with pytest.raises(IntegrityInvariantError):
        p.check_invariants()


def test_eviction_invariant_failure_fails_shard(tmp_path):
    disk, meta, ms, sh = _build_persisted(tmp_path)
    cold, shard = _cold_shard(disk, meta)
    _scan(shard)                       # page everything in
    assert len(shard.paged) == N_SERIES
    # re-materialize one partition as live so there is something to evict
    rec_tags = {"_metric_": "im", "inst": "i0", "_ws_": "w", "_ns_": "n"}
    part = shard.create_partition("gauge", rec_tags, T0)
    part.ingest(T0 + N_ROWS * STEP, (1.0,))
    shard.paged._bytes += 13           # corrupt the reclaim bookkeeping
    with pytest.raises(IntegrityInvariantError):
        shard.evict_partitions(1)
    assert shard.integrity_failed is not None
    # the shard now refuses to serve rather than risk stale buffers
    with pytest.raises(IntegrityInvariantError):
        _scan(shard)
    with pytest.raises(IntegrityInvariantError):
        shard.lookup_partitions(FILTERS, 0, 2**62)


# ---------------------------------------------------------------------------
# Query path: partial-data warning + /admin/integrity + re-query exclusion
# ---------------------------------------------------------------------------


def _http_get(port, path, **params):
    import urllib.parse
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def test_query_partial_data_warning_and_admin_endpoint(tmp_path):
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
    from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus

    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(3).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    cold, shard = _cold_shard(disk, meta)
    mapper = ShardMapper(1)
    mapper.register_node([0], "local")
    mapper.update_status(0, ShardStatus.ACTIVE)
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=0)
    srv = FiloHttpServer()
    srv.bind_dataset(DatasetBinding("prom", cold, planner))
    port = srv.start()
    try:
        args = {"query": "im", "start": T0 // 1000,
                "end": (T0 + (N_ROWS - 1) * STEP) // 1000, "step": "10s"}
        status, headers, body = _http_get(
            port, "/promql/prom/api/v1/query_range", **args)
        assert status == 200 and body["status"] == "success"
        # the first query detects + already warns: partial, not silence
        assert any("corrupt" in w for w in body.get("warnings", ())), body
        assert headers.get("X-FiloDB-Partial-Data") == "true"
        assert len(body["data"]["result"]) == N_SERIES - 1
        # re-query: quarantine exclusion, warning persists
        status, headers, body = _http_get(
            port, "/promql/prom/api/v1/query_range", **args)
        assert any("corrupt" in w for w in body.get("warnings", ()))
        assert headers.get("X-FiloDB-Partial-Data") == "true"
        # the integrity counters are visible via /admin/integrity
        status, _h, admin = _http_get(port, "/admin/integrity")
        assert status == 200
        data = admin["data"]
        assert data["counters"]["checksum_failures"] >= 1
        assert data["quarantine"]["quarantined_chunks"] >= 1
        assert any(d["chunk_id"] == cid for d in data["quarantined"])
        (row,) = data["shards"]["prom"]
        assert row["shard"] == 0
        assert row["paged_cache_invariants"] == "ok"
        assert row["integrity_failed"] is None
    finally:
        srv.shutdown()


def test_checksum_detection_reaches_shard_stats(tmp_path):
    """Store-level detections must reach the owning shard's stats (the
    tentpole's 'counted in shard stats'), not just global counters."""
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(3).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    cold, shard = _cold_shard(disk, meta)
    epoch0 = shard.removal_epoch
    _scan(shard)
    assert shard.stats.chunks_corrupt >= 1
    assert shard.stats.chunks_quarantined == 1
    # grid plans staged from the chunk must revalidate too
    assert shard.removal_epoch > epoch0


def test_verify_switch_actually_disables_bulk_path(tmp_path):
    """FILODB_INTEGRITY_VERIFY=0 / set_verify(False) must disable the
    deferred decoder-side verification too — the A/B overhead
    measurement depends on the OFF arm being genuinely off."""
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(3).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    integrity.set_verify(False)
    try:
        cold, shard = _cold_shard(disk, meta)
        tags, _ = _scan(shard)
        # verification off: the corrupt chunk sails through undetected
        assert shard.stats.page_decode_corrupt == 0
        assert not QUARANTINE
    finally:
        integrity.set_verify(True)


def test_partial_warning_scoped_to_query_time_range(tmp_path):
    """A quarantined chunk OUTSIDE the queried window excluded nothing
    from the result — it must not flag that query as partial."""
    disk, meta, ms, sh = _build_persisted(tmp_path)
    pk, cid = FaultInjector(3).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    cold, shard = _cold_shard(disk, meta)
    _scan(shard)                       # detect + quarantine (with range)
    assert QUARANTINE.is_quarantined(pk, cid)
    from filodb_tpu.query.exec import ExecContext, MultiSchemaPartitionsExec
    far = T0 + 10 * 24 * 3600 * 1000   # window far past all data
    plan = MultiSchemaPartitionsExec("prom", 0, FILTERS, far,
                                     far + 60_000)
    res = plan.execute(ExecContext(cold))
    assert res.stats.corrupt_chunks_excluded == 0
    # ...but a window overlapping the chunk IS flagged
    plan = MultiSchemaPartitionsExec("prom", 0, FILTERS, 0, 2**61)
    res = plan.execute(ExecContext(cold))
    assert res.stats.corrupt_chunks_excluded == 1


def test_clean_run_trips_nothing(tmp_path):
    """The zero-false-positive guarantee: a full ingest -> flush ->
    cold page-in -> query cycle with NO injected fault must not bump a
    single failure counter or quarantine anything."""
    before = _metric_totals()
    disk, meta, ms, sh = _build_persisted(tmp_path)
    cold, shard = _cold_shard(disk, meta)
    tags, batch = _scan(shard)
    assert len(tags) == N_SERIES
    assert not QUARANTINE
    after = _metric_totals()
    for key in ("checksum_failures", "decode_failures",
                "invariant_failures", "partial_queries"):
        assert after[key] == before[key], key
    assert after["chunks_verified"] > before["chunks_verified"]
    assert shard.stats.chunks_corrupt == 0
    assert shard.stats.page_decode_corrupt == 0


# ---------------------------------------------------------------------------
# Offline verify-chunks scan + CLI
# ---------------------------------------------------------------------------


def test_verify_chunks_reports_counts(tmp_path):
    disk, meta, ms, sh = _build_persisted(tmp_path)
    report = verify_chunks(disk, "prom", deep=True)
    assert report["shards"][0]["chunks"] == N_SERIES
    assert report["shards"][0]["passed"] == N_SERIES
    assert report["total_failed"] == 0
    pk, cid = FaultInjector(9).corrupt_stored_chunk(disk, "prom", 0,
                                                    mode="flip")
    report = verify_chunks(disk, "prom", deep=False)
    assert report["total_failed"] == 1
    assert report["shards"][0]["failed"] == 1
    assert report["shards"][0]["passed"] == N_SERIES - 1
    (failure,) = report["shards"][0]["failures"]
    assert "checksum" in failure and str(cid) in failure


def test_verify_chunks_cli(tmp_path, capsys):
    from filodb_tpu import cli
    _build_persisted(tmp_path)
    rc = cli.main(["verify-chunks", "--data-dir", str(tmp_path),
                   "--dataset", "prom"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["total_failed"] == 0
    disk = DiskColumnStore(str(tmp_path / "chunks.db"))
    FaultInjector(9).corrupt_stored_chunk(disk, "prom", 0, mode="flip")
    rc = cli.main(["verify-chunks", "--data-dir", str(tmp_path),
                   "--dataset", "prom", "--deep"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and report["total_failed"] == 1


# ---------------------------------------------------------------------------
# Satellites: native span-helper bounds + influx zero-length heads
# ---------------------------------------------------------------------------


def test_native_span_helper_bounds():
    if not native.enable():
        pytest.skip("native library unavailable")
    npr = native.influx_parser()
    a = np.frombuffer(b"abcdef", np.uint8)
    # out-of-bounds spans return the -1 sentinel (None), never read OOB
    assert npr.gather(a, np.array([0]), np.array([99])) is None
    assert npr.gather(a, np.array([-1]), np.array([3])) is None
    assert npr.gather(a, np.array([0, 3]), np.array([3, 6])) is not None
    p1 = np.arange(1, 65, dtype=np.uint64)
    p2 = np.arange(2, 66, dtype=np.uint64)
    assert npr.head_hashes(a, np.array([-1]), np.array([2]), p1, p2) is None
    assert npr.head_hashes(a, np.array([3]), np.array([7]), p1, p2) is None
    assert npr.head_hashes(a, np.array([0]), np.array([6]), p1, p2) \
        is not None
    assert npr.verify(a, np.array([0]), np.array([99]),
                      np.array([0])) is None
    assert npr.verify(a, np.array([0, 0]), np.array([3, 3]),
                      np.array([0, 0])) is True


def test_influx_zero_length_head_falls_back():
    from filodb_tpu.gateway.influx import parse_batch_columns, parse_lines_fast
    good = "m,t=a v=1.5 1700000000000000000\n"
    assert parse_batch_columns(good * 3) is not None
    # a line whose head is empty (leading space) must reject the batch:
    # np.add.reduceat would diverge from the C head_hash128 on a
    # zero-length segment (ADVICE r5 finding 3)
    bad = good + " x=1.5 1700000000000000000\n"
    assert parse_batch_columns(bad) is None
    # the per-line fallback still parses the healthy lines
    recs = parse_lines_fast(good * 2)
    assert len(recs) == 2 and recs[0].measurement == "m"
