"""Workload-management subsystem (ISSUE 5): cost model calibration,
admission shed under synthetic overload (429 + Retry-After, bounded
high-priority latency), deadline enforcement in the scheduler, tenant
cardinality quotas on ingest, and dispatch retry/hedge behavior under
faultinject-driven connection failures."""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.query.model import (QueryContext, QueryResult, QueryStats,
                                    ShardUnavailable)
from filodb_tpu.query.scheduler import QueryRejected, QueryScheduler
from filodb_tpu.utils.observability import REGISTRY
from filodb_tpu.workload import deadline as wdl
from filodb_tpu.workload.admission import (AdmissionController,
                                           AdmissionRejected, plan_tenant)
from filodb_tpu.workload.cost import CostModel
from filodb_tpu.workload.quota import SeriesQuota

BASE = 1_700_000_000_000
STEP = 10_000


def _qctx(timeout_ms=30_000, tenant="", priority="default",
          deadline_in_ms=None):
    q = QueryContext(submit_time_ms=int(time.time() * 1000),
                     timeout_ms=timeout_ms, tenant=tenant,
                     priority=priority)
    if deadline_in_ms is not None:
        q.deadline_ms = int(time.time() * 1000) + deadline_in_ms
    else:
        wdl.mint(q)
    return q


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------


def _ingested_store(n_series=32, num_shards=4, spread=2):
    from filodb_tpu.core.record import RecordBuilder, decode_container
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(7)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], DatasetOptions())
    ts = BASE + np.arange(120, dtype=np.int64) * STEP
    for i in range(n_series):
        b.add_series(ts, [np.cumsum(rng.random(120))],
                     {"__name__": "wl_total", "instance": f"i{i}",
                      "_ws_": "demo", "_ns_": "App-0"})
    for off, c in enumerate(b.containers()):
        per = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                        spread) % num_shards
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard("prom", sh).ingest(recs, off)
    return ms, mapper


def _plan(ms, mapper, query, start, end, spread=2):
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import DatasetOptions
    from filodb_tpu.promql.parser import query_range_to_logical_plan
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=spread)
    lp = query_range_to_logical_plan(query, start, STEP, end)
    return planner.materialize(lp, QueryContext())


class TestCostModel:
    def test_monotone_in_time_range(self):
        ms, mapper = _ingested_store()
        cm = CostModel()
        q = 'sum(rate(wl_total{_ws_="demo",_ns_="App-0"}[2m]))'
        short = cm.estimate(_plan(ms, mapper, q, BASE, BASE + 600_000), ms)
        long = cm.estimate(_plan(ms, mapper, q, BASE, BASE + 6_000_000), ms)
        assert long > short > 0

    def test_monotone_in_series_hits(self):
        ms, mapper = _ingested_store(n_series=8)
        ms2, mapper2 = _ingested_store(n_series=64)
        cm = CostModel()
        q = 'sum(rate(wl_total{_ws_="demo",_ns_="App-0"}[2m]))'
        few = cm.estimate(
            _plan(ms, mapper, q, BASE, BASE + 600_000), ms)
        many = cm.estimate(
            _plan(ms2, mapper2, q, BASE, BASE + 600_000), ms2)
        assert many > few

    def test_heavier_ops_cost_more(self):
        ms, mapper = _ingested_store()
        cm = CostModel()
        plain = cm.estimate(_plan(
            ms, mapper, 'rate(wl_total{_ws_="demo",_ns_="App-0"}[2m])',
            BASE, BASE + 600_000), ms)
        heavy = cm.estimate(_plan(
            ms, mapper,
            'quantile_over_time(0.99, '
            'wl_total{_ws_="demo",_ns_="App-0"}[2m])',
            BASE, BASE + 600_000), ms)
        assert heavy > plain

    def test_calibration_tracks_observed_throughput(self):
        cm = CostModel(sec_per_unit=1e-4)
        # estimate is linear in cost (monotone by construction)
        assert cm.estimate_seconds(200) > cm.estimate_seconds(100)
        # observe consistently FASTER execution: predictions drop
        # monotonically toward the observed rate
        before = cm.estimate_seconds(1000)
        preds = []
        for _ in range(10):
            cm.observe(cost=1000, seconds=0.001)  # 1e-6 s/unit
            preds.append(cm.estimate_seconds(1000))
        assert preds[0] <= before
        assert all(a >= b for a, b in zip(preds, preds[1:]))
        assert preds[-1] == pytest.approx(0.001, rel=0.5)
        # and SLOWER observations push it back up
        cm.observe(cost=1000, seconds=1.0)
        assert cm.estimate_seconds(1000) > preds[-1]

    def test_calibration_upward_moves_are_rate_limited(self):
        """One compile-inflated cold-start sample must not wedge
        admission: shed queries never observe, so an overshoot past the
        shed threshold could never self-correct."""
        cm = CostModel(sec_per_unit=1e-5)
        cm.observe(cost=1, seconds=10.0)  # 1e6x the prior (jit compile)
        assert cm.estimate_seconds(1) <= 1e-5 * 4 + 1e-12
        # a genuinely slow node still converges upward, geometrically
        for _ in range(10):
            cm.observe(cost=1, seconds=10.0)
        assert cm.estimate_seconds(1) > 1e-3

    def test_remote_leaf_inherits_mean_of_resolved(self):
        ms, mapper = _ingested_store(num_shards=4)
        cm = CostModel()
        plan = _plan(ms, mapper,
                     'sum(rate(wl_total{_ws_="demo",_ns_="App-0"}[2m]))',
                     BASE, BASE + 600_000)
        full = cm.estimate(plan, ms)
        # without a memstore no leaf resolves: the default prior kicks
        # in and the estimate stays positive (never free)
        blind = cm.estimate(plan, None)
        assert blind >= 1.0 and full >= 1.0


class TestDeadline:
    def test_mint_and_remaining(self):
        q = QueryContext(submit_time_ms=int(time.time() * 1000),
                         timeout_ms=5_000)
        wdl.mint(q)
        rem = wdl.remaining_ms(q)
        assert 0 < rem <= 5_000
        assert not wdl.expired(q)
        assert wdl.remaining_ms(QueryContext()) is None

    def test_budget_caps_timeout(self):
        q = _qctx(deadline_in_ms=200)
        assert wdl.budget_timeout_s(q, 60.0) <= 0.2
        # no deadline: the cap rules
        assert wdl.budget_timeout_s(QueryContext(), 60.0) == 60.0
        # expired: fail-fast floor, not urllib's 0=forever
        q2 = _qctx(deadline_in_ms=-50)
        assert 0 < wdl.budget_timeout_s(q2, 60.0) <= 0.01

    def test_check_raises_when_expired(self):
        with pytest.raises(wdl.DeadlineExceeded):
            wdl.check(_qctx(deadline_in_ms=-10))
        wdl.check(_qctx(deadline_in_ms=10_000))  # plenty left: no raise

    def test_wire_budget_shrinks_across_serialization(self):
        from filodb_tpu.query import wire
        from filodb_tpu.query.exec import MultiSchemaPartitionsExec
        qctx = _qctx(deadline_in_ms=1_000)
        plan = MultiSchemaPartitionsExec("prom", 0, [], 0, 1,
                                         query_context=qctx)
        p1 = wire.serialize_plan(plan)
        assert 0 < p1["qctx"]["budget_ms"] <= 1_000
        assert "deadline_ms" not in p1["qctx"]
        time.sleep(0.06)
        p2 = wire.serialize_plan(plan)
        assert p2["qctx"]["budget_ms"] < p1["qctx"]["budget_ms"]
        # decode re-anchors on the local clock
        d = wire.deserialize_plan(p2)
        rem = wdl.remaining_ms(d.query_context)
        assert 0 < rem <= p2["qctx"]["budget_ms"] + 1

    def test_expired_plan_refuses_to_execute(self):
        from filodb_tpu.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.query.exec import EmptyResultExec, ExecContext
        from filodb_tpu.query.model import QueryError
        qctx = _qctx(deadline_in_ms=-5)
        plan = EmptyResultExec(query_context=qctx)
        with pytest.raises(QueryError, match="deadline"):
            plan.execute(ExecContext(TimeSeriesMemStore(), qctx))


# ---------------------------------------------------------------------------
# Admission controller (unit)
# ---------------------------------------------------------------------------


class TestAdmission:
    def _ctrl(self, **kw):
        kw.setdefault("max_inflight_cost", 10.0)
        kw.setdefault("workers", 1)
        return AdmissionController(CostModel(sec_per_unit=1e-6), **kw)

    def test_admits_and_releases(self):
        c = self._ctrl()
        with c.admit(_qctx(), 5.0):
            assert c.snapshot()["inflight_cost"] == 5.0
        assert c.snapshot()["inflight_cost"] == 0.0

    def test_overload_sheds_with_retry_after(self):
        c = self._ctrl()
        with c.admit(_qctx(), 8.0):
            with pytest.raises(AdmissionRejected) as exc:
                c.admit(_qctx(), 8.0)
            assert exc.value.reason == "overload"
            assert exc.value.retry_after_s >= 1.0

    def test_priority_headroom(self):
        """default saturates at its 80% share; high still admits."""
        c = self._ctrl()
        with c.admit(_qctx(priority="default"), 7.0):
            with pytest.raises(AdmissionRejected):
                c.admit(_qctx(priority="default"), 2.0)  # 7+2 > 8
            with c.admit(_qctx(priority="high"), 2.0):   # 7+2 <= 10
                pass
            with pytest.raises(AdmissionRejected):
                c.admit(_qctx(priority="low"), 1.0)      # 7+1 > 5

    def test_expired_rejected_before_queueing(self):
        c = self._ctrl()
        with pytest.raises(AdmissionRejected) as exc:
            c.admit(_qctx(deadline_in_ms=-10), 1.0)
        assert exc.value.reason == "expired"

    def test_queue_delay_exceeding_deadline_sheds(self):
        # calibrate slow: 1 unit = 1s at 1 worker
        c = AdmissionController(CostModel(sec_per_unit=1.0),
                                max_inflight_cost=1000.0, workers=1)
        with c.admit(_qctx(), 5.0):  # ~5s of work in flight
            with pytest.raises(AdmissionRejected) as exc:
                c.admit(_qctx(deadline_in_ms=500), 1.0)
            assert exc.value.reason == "deadline"

    def test_tenant_concurrency_cap(self):
        c = self._ctrl(tenant_max_concurrent=1)
        with c.admit(_qctx(tenant="t1"), 1.0):
            with pytest.raises(AdmissionRejected) as exc:
                c.admit(_qctx(tenant="t1"), 1.0)
            assert exc.value.reason == "tenant_concurrency"
            with c.admit(_qctx(tenant="t2"), 1.0):  # other tenants fine
                pass

    def test_tenant_cost_budget(self):
        c = self._ctrl(max_inflight_cost=100.0,
                       tenant_max_inflight_cost=3.0)
        with c.admit(_qctx(tenant="t1"), 3.0):
            with pytest.raises(AdmissionRejected) as exc:
                c.admit(_qctx(tenant="t1"), 1.0)
            assert exc.value.reason == "tenant_cost"

    def test_disabled_admits_everything(self):
        c = self._ctrl(enabled=False)
        with c.admit(_qctx(deadline_in_ms=-10), 1e9):
            pass

    def test_partial_priority_shares_merge_over_defaults(self):
        """A config naming only one class must not strip the others —
        every unlabelled query lands in 'default'."""
        c = AdmissionController(CostModel(), max_inflight_cost=10.0,
                                priority_shares={"high": 1.0}, workers=1)
        with c.admit(_qctx(priority="default"), 1.0):  # no KeyError
            pass
        assert c.priority_shares["default"] == 0.8
        # unknown classes fall back to the default class's share
        with c.admit(_qctx(priority="mystery"), 1.0):
            pass

    def test_runtime_configure(self):
        c = self._ctrl()
        c.configure(max_inflight_cost=1.0)
        with pytest.raises(AdmissionRejected):
            c.admit(_qctx(), 2.0)
        c.configure(max_inflight_cost=100.0)
        with c.admit(_qctx(), 2.0):
            pass

    def test_plan_tenant_from_filters(self):
        ms, mapper = _ingested_store()
        ep = _plan(ms, mapper,
                   'sum(rate(wl_total{_ws_="demo",_ns_="App-0"}[2m]))',
                   BASE, BASE + 600_000)
        assert plan_tenant(ep) == "demo/App-0"


# ---------------------------------------------------------------------------
# Scheduler: expired-at-dequeue drop (satellite bugfix)
# ---------------------------------------------------------------------------


class TestSchedulerDeadline:
    def test_expired_deadline_dropped_at_dequeue(self):
        s = QueryScheduler(num_workers=1, max_queued=8, name="wl-exp")
        expired = REGISTRY.counter("filodb_query_sched_expired_total")
        before = expired.value(scheduler="wl-exp")
        try:
            gate = threading.Event()
            started = threading.Event()
            ran = []
            s.submit(lambda: started.set() or gate.wait(5))
            started.wait(5)
            fut = s.submit(lambda: ran.append(1),
                           deadline_ms=int(time.time() * 1000) + 20)
            time.sleep(0.1)  # deadline passes while queued
            gate.set()
            with pytest.raises(QueryRejected, match="deadline expired"):
                fut.result(timeout=5)
            assert not ran, "expired query must NEVER execute"
            assert expired.value(scheduler="wl-exp") == before + 1
        finally:
            s.shutdown()

    def test_live_deadline_executes(self):
        s = QueryScheduler(num_workers=1, max_queued=8, name="wl-live")
        try:
            assert s.execute(lambda: 7,
                             deadline_ms=int(time.time() * 1000)
                             + 10_000) == 7
        finally:
            s.shutdown()


# ---------------------------------------------------------------------------
# Cardinality quotas on ingest
# ---------------------------------------------------------------------------


class TestSeriesQuota:
    def _shard(self, quota):
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        from filodb_tpu.memstore.shard import TimeSeriesShard
        sh = TimeSeriesShard("prom", DEFAULT_SCHEMAS, 0)
        sh.series_quota = quota
        return sh

    def _ingest_one(self, sh, ns, instance, ts=BASE):
        from filodb_tpu.core.record import IngestRecord, partition_hash
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        tags = {"_metric_": "q_total", "_ws_": "demo", "_ns_": ns,
                "instance": instance}
        rec = IngestRecord(DEFAULT_SCHEMAS["gauge"].schema_hash, tags, ts,
                           (1.0,), 0, partition_hash(tags))
        return sh.ingest([rec], offset=sh.latest_offset + 1)

    def test_over_quota_new_series_rejected(self):
        quota = SeriesQuota(dataset="prom", default_limit=2)
        sh = self._shard(quota)
        rejected = REGISTRY.counter("filodb_quota_rejected_series_total")
        before = rejected.value(dataset="prom", tenant="App-7")
        assert self._ingest_one(sh, "App-7", "a") == 1
        assert self._ingest_one(sh, "App-7", "b") == 1
        # third NEW series is over quota: rows dropped, counted
        assert self._ingest_one(sh, "App-7", "c") == 0
        assert sh.stats.series_quota_rejected == 1
        assert sh.stats.rows_quota_dropped == 1
        assert rejected.value(dataset="prom", tenant="App-7") == before + 1
        # EXISTING series keep ingesting
        assert self._ingest_one(sh, "App-7", "a", ts=BASE + 60_000) == 1
        # other tenants are unaffected
        assert self._ingest_one(sh, "App-8", "a") == 1
        assert quota.active("App-7") == 2

    def test_override_beats_default(self):
        quota = SeriesQuota(dataset="prom", default_limit=100,
                            overrides={"Bomb": 1})
        sh = self._shard(quota)
        assert self._ingest_one(sh, "Bomb", "a") == 1
        assert self._ingest_one(sh, "Bomb", "b") == 0

    def test_purge_frees_quota(self):
        quota = SeriesQuota(dataset="prom", default_limit=1)
        sh = self._shard(quota)
        assert self._ingest_one(sh, "App-7", "a") == 1
        assert self._ingest_one(sh, "App-7", "b") == 0
        # age the series out entirely; quota frees with the index slot
        sh.purge_expired(retention_ms=1, now_ms=BASE + 3_600_000)
        assert quota.active("App-7") == 0
        assert self._ingest_one(sh, "App-7", "b", ts=BASE + 60_000) == 1

    def test_refresh_from_index(self):
        quota = SeriesQuota(dataset="prom", default_limit=100)
        sh = self._shard(None)  # unmetered ingest
        for i in range(5):
            self._ingest_one(sh, "App-1", f"i{i}")
        for i in range(3):
            self._ingest_one(sh, "App-2", f"i{i}")
        quota.refresh_from_index(sh.index)
        assert quota.active("App-1") == 5
        assert quota.active("App-2") == 3

    def test_gateway_edge_sheds_over_quota_tenant(self):
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        from filodb_tpu.gateway.server import ShardingPublisher
        from filodb_tpu.parallel.shardmap import ShardMapper
        quota = SeriesQuota(dataset="prom", tenant_label="_ns_",
                            default_limit=0, overrides={"Ok": 100})
        published = []
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], ShardMapper(4),
                                lambda s, c: published.append((s, c)),
                                quota=quota)
        dropped = REGISTRY.counter("filodb_quota_dropped_samples_total")
        before = dropped.value(dataset="prom", tenant="Bomb")
        lines = "\n".join(
            [f"m,_ws_=demo,_ns_=Bomb,i=i{k} v=1 1700000000000000000"
             for k in range(4)]
            + [f"m,_ws_=demo,_ns_=Ok,i=i{k} v=1 1700000000000000000"
               for k in range(4)]) + "\n"
        n = pub.ingest_influx_batch(lines)
        assert n == 4  # only the under-quota tenant's samples landed
        assert dropped.value(dataset="prom", tenant="Bomb") == before + 4
        # quota freed later: the series are NOT poisoned by a memo
        quota.configure(default_limit=100)
        assert pub.ingest_influx_batch(lines) == 8


# ---------------------------------------------------------------------------
# HTTP overload e2e: shed with 429, bounded high-priority latency
# ---------------------------------------------------------------------------


class _SleepPlan:
    """Fake ExecPlan: burns wall time, returns an empty result."""

    def __init__(self, qctx, sleep_s):
        self.query_context = qctx
        self.transformers = []
        self.children = ()
        self._sleep_s = sleep_s

    def execute(self, ctx):
        time.sleep(self._sleep_s)
        return QueryResult(self.query_context.query_id, [], QueryStats())


class _SleepPlanner:
    def __init__(self, sleep_s):
        self.sleep_s = sleep_s

    def materialize(self, lp, qctx):
        return _SleepPlan(qctx, self.sleep_s)


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture()
def overload_server():
    """One dataset whose every query sleeps 150ms, 2 workers, a global
    admission budget of 4 cost units (each query costs 1): capacity is
    ~13 qps, the test offers 4x that concurrently."""
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
    from filodb_tpu.memstore.memstore import TimeSeriesMemStore
    ctrl = AdmissionController(CostModel(sec_per_unit=0.15),
                               dataset="ovl", max_inflight_cost=4.0,
                               priority_shares={"low": 0.25,
                                                "default": 0.5,
                                                "high": 1.0},
                               workers=2)
    sched = QueryScheduler(num_workers=2, max_queued=64, name="ovl")
    srv = FiloHttpServer()
    srv.bind_dataset(DatasetBinding(
        "ovl", TimeSeriesMemStore(), _SleepPlanner(0.15),
        scheduler=sched, admission=ctrl))
    port = srv.start()
    yield port, ctrl
    srv.shutdown()
    sched.shutdown()
    ctrl.shutdown()


class TestOverloadShed(object):
    QS = {"query": "up", "start": 1_700_000_000, "end": 1_700_000_060,
          "step": "15s"}

    def test_excess_load_sheds_429_high_priority_stays_bounded(
            self, overload_server):
        port, ctrl = overload_server
        results = []
        lock = threading.Lock()

        def fire(priority, n):
            for _ in range(n):
                t0 = time.perf_counter()
                code, body, headers = _get(
                    port, "/promql/ovl/api/v1/query_range",
                    priority=priority, **self.QS)
                with lock:
                    results.append((priority, code,
                                    time.perf_counter() - t0, headers))

        # 16 concurrent default-priority clients (4x the cost budget),
        # plus 2 high-priority clients issuing 2 queries each (2
        # concurrent highs always fit the reserved headroom: 2 default
        # ceiling + 2 high <= the 4-unit global budget)
        threads = [threading.Thread(target=fire, args=("default", 2))
                   for _ in range(16)]
        threads += [threading.Thread(target=fire, args=("high", 2))
                    for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        default = [r for r in results if r[0] == "default"]
        high = [r for r in results if r[0] == "high"]
        shed = [r for r in default if r[1] == 429]
        ok_default = [r for r in default if r[1] == 200]
        assert shed, "4x overload produced no 429 sheds"
        assert ok_default, "admission must not shed EVERYTHING"
        # every shed reply carries a Retry-After hint
        for _p, _c, _lat, headers in shed:
            assert int(headers["Retry-After"]) >= 1
        # shed queries answer fast — never queued to rot
        assert max(lat for _p, _c, lat, _h in shed) < 2.0
        # high priority: all answered, p50 bounded (reserved headroom
        # above the default-class ceiling keeps them flowing)
        assert all(c == 200 for _p, c, _l, _h in high), high
        lats = sorted(lat for _p, _c, lat, _h in high)
        assert lats[len(lats) // 2] < 2.0, f"high-priority p50 {lats}"

    def test_expired_deadline_is_shed_not_executed(self, overload_server):
        port, _ctrl = overload_server
        done = REGISTRY.counter("filodb_queries_executed_total")
        before = done.value(scheduler="ovl")
        code, body, headers = _get(
            port, "/promql/ovl/api/v1/query_range",
            timeout="1ms", **self.QS)
        assert code == 429
        assert body["errorType"] == "throttled"
        assert "Retry-After" in headers
        assert done.value(scheduler="ovl") == before

    def test_admin_workload_view(self, overload_server):
        port, _ctrl = overload_server
        code, body, _ = _get(port, "/admin/workload")
        assert code == 200
        row = body["data"]["datasets"]["ovl"]
        assert "admission" in row and row["queue_depth"] >= 0
        assert row["admission"]["max_inflight_cost"] == 4.0

    def test_runtime_config_adjusts_admission(self, overload_server):
        port, ctrl = overload_server
        code, body, _ = _get(port, "/admin/config",
                             **{"admission-max-inflight-cost": "2.5"})
        assert code == 200
        assert ctrl.max_inflight_cost == 2.5
        wl = body["data"]["workload"]["datasets"]["ovl"]
        assert wl["admission"]["max_inflight_cost"] == 2.5


# ---------------------------------------------------------------------------
# Dispatch retry / hedge under injected connection faults
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def data_node():
    """A real single-node /execplan backend with a little data."""
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import DatasetOptions
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
    ms, mapper = _ingested_store(n_series=8, num_shards=1, spread=0)
    srv = FiloHttpServer()
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=0)
    srv.bind_dataset(DatasetBinding("prom", ms, planner))
    port = srv.start()
    yield {"port": port, "ms": ms}
    srv.shutdown()


def _leaf_plan(deadline_in_ms=None):
    from filodb_tpu.core.filters import ColumnFilter, Equals
    from filodb_tpu.query.exec import MultiSchemaPartitionsExec
    qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
    if deadline_in_ms is not None:
        qctx.deadline_ms = int(time.time() * 1000) + deadline_in_ms
    return MultiSchemaPartitionsExec(
        "prom", 0, [ColumnFilter("_metric_", Equals("wl_total"))],
        BASE, BASE + 600_000, query_context=qctx)


def _exec_ctx(ms):
    from filodb_tpu.query.exec import ExecContext
    return ExecContext(ms, QueryContext())


class TestDispatchRetryHedge:
    def test_connection_fault_is_retried(self, data_node):
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        proxy = FlakyTcpProxy(data_node["port"])
        port = proxy.start()
        retries = REGISTRY.counter("filodb_dispatch_retries_total")
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   max_retries=2, backoff_s=0.01)
            before = retries.value(endpoint=d.endpoint)
            proxy.fail_next(1)
            result = d.dispatch(_leaf_plan(), _exec_ctx(data_node["ms"]))
            assert result.num_series > 0
            assert proxy.connections == 2  # refused once, then retried
            assert retries.value(endpoint=d.endpoint) == before + 1
        finally:
            proxy.shutdown()

    def test_exhausted_retries_raise_shard_unavailable(self, data_node):
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        proxy = FlakyTcpProxy(data_node["port"])
        port = proxy.start()
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   max_retries=1, backoff_s=0.01)
            proxy.fail_next(5)
            with pytest.raises(ShardUnavailable):
                d.dispatch(_leaf_plan(), _exec_ctx(data_node["ms"]))
            assert proxy.connections == 2  # 1 + 1 retry, bounded
        finally:
            proxy.shutdown()

    def test_deadline_caps_dispatch_timeout(self, data_node):
        """Satellite #1: the fixed 60s dispatch timeout is gone — a
        stalled backend costs at most the remaining budget."""
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        proxy = FlakyTcpProxy(data_node["port"], stall_s=5.0)
        port = proxy.start()
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   timeout_s=60.0, max_retries=0)
            proxy.stall_next(1)
            t0 = time.perf_counter()
            with pytest.raises(ShardUnavailable):
                d.dispatch(_leaf_plan(deadline_in_ms=300),
                           _exec_ctx(data_node["ms"]))
            assert time.perf_counter() - t0 < 2.0, \
                "dispatch waited past the deadline budget"
        finally:
            proxy.shutdown()

    def test_hedged_request_beats_tail_stall(self, data_node):
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        proxy = FlakyTcpProxy(data_node["port"], stall_s=2.0)
        port = proxy.start()
        hedged = REGISTRY.counter("filodb_dispatch_hedged_total")
        wins = REGISTRY.counter("filodb_dispatch_hedge_wins_total")
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   max_retries=0, hedge=True,
                                   hedge_min_s=0.05, hedge_warmup=4)
            ms = data_node["ms"]
            for _ in range(4):  # warm the p99 window
                d.dispatch(_leaf_plan(), _exec_ctx(ms))
            assert d.hedge_delay_s() is not None
            b_h = hedged.value(endpoint=d.endpoint)
            b_w = wins.value(endpoint=d.endpoint)
            proxy.stall_next(1)  # primary stalls 2s; hedge passes
            t0 = time.perf_counter()
            result = d.dispatch(_leaf_plan(), _exec_ctx(ms))
            elapsed = time.perf_counter() - t0
            assert result.num_series > 0
            assert elapsed < 1.5, \
                f"hedge did not beat the {proxy.stall_s}s stall: {elapsed}"
            assert hedged.value(endpoint=d.endpoint) == b_h + 1
            assert wins.value(endpoint=d.endpoint) == b_w + 1
        finally:
            proxy.shutdown()

    def test_retry_reserializes_the_wire_budget(self, data_node):
        """A retried attempt must rebuild the body so its relative
        budget_ms reflects what is left NOW — a stale body would let
        the data node re-anchor budget the coordinator already spent."""
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        from filodb_tpu.query import wire
        proxy = FlakyTcpProxy(data_node["port"])
        port = proxy.start()
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   max_retries=2, backoff_s=0.05)
            plan = _leaf_plan(deadline_in_ms=10_000)
            budgets = []

            def make_body():
                payload = wire.serialize_plan(plan)
                budgets.append(payload["qctx"]["budget_ms"])
                return json.dumps(payload).encode()

            proxy.fail_next(1)
            d._request(plan, make_body, {"Content-Type":
                                         "application/json"})
            assert len(budgets) == 2, "retry must re-serialize the body"
            assert budgets[1] < budgets[0], \
                "the retried attempt's wire budget must have shrunk"
        finally:
            proxy.shutdown()

    def test_http_error_is_not_retried(self, data_node):
        """A served error response must never multiply load."""
        from filodb_tpu.coordinator.dispatch import HttpPlanDispatcher
        from filodb_tpu.integrity.faultinject import FlakyTcpProxy
        proxy = FlakyTcpProxy(data_node["port"])
        port = proxy.start()
        try:
            d = HttpPlanDispatcher(f"http://127.0.0.1:{port}",
                                   max_retries=3, backoff_s=0.01)
            plan = _leaf_plan()
            plan.dataset = "nope"  # 404 from the data node
            from filodb_tpu.query.model import QueryError
            with pytest.raises(QueryError):
                d.dispatch(plan, _exec_ctx(data_node["ms"]))
            assert proxy.connections == 1, "HTTP errors must not retry"
        finally:
            proxy.shutdown()
