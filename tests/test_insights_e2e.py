"""Fleet workload insights e2e (ISSUE 19 acceptance criteria).

1. A partitioned query stream across a 3-node in-process cluster:
   ``/admin/fleet`` (from ANY vantage node) equals the EXACT
   ``merge_snapshots`` of the three ``/admin/insights?raw=true``
   snapshots — bit-identical integers, no tolerance.
2. A shape-identical concurrent burst measures batching headroom > 1
   (the empirical number ROADMAP item 2 needs).
3. An unreachable peer is marked stale/error in the fleet view; the
   view itself still serves.
4. An injected latency fault (every query breaching a tiny SLO latency
   threshold) drives the ``FiloTenantSLOFastBurn`` alert through
   inactive -> pending -> firing via the normal self-scrape + rules
   machinery, with the burn visible in the ``filodb_slo_*`` families.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.insights.fleet import FleetAggregator
from filodb_tpu.insights.ledger import WorkloadLedger, merge_snapshots
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.standalone import FiloServer

BASE = 1_700_000_000_000
STEP = 10_000
NODES = ("fi-a", "fi-b", "fi-c")
WINDOW = (BASE + 60_000, BASE + 600_000)


def _get(port, path, timeout=30, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get_text(port, path, timeout=30):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as resp:
        return resp.read().decode()


def _query(port, promql):
    return _get(port, "/promql/prom/api/v1/query_range", query=promql,
                start=WINDOW[0] / 1000, end=WINDOW[1] / 1000, step="30s")


def _wait(predicate, timeout_s, what, interval=0.03):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {what}")


@pytest.fixture(scope="module")
def fleet_cluster():
    """Three bare HTTP servers, each a one-shard coordinator over its
    own memstore + ledger — the in-process stand-in for three
    standalone nodes (the WatermarkLedger lesson: per-server state)."""
    servers, ports = {}, {}
    rng = np.random.default_rng(11)
    for name in NODES:
        mapper = ShardMapper(1)
        mapper.register_node([0], name)
        mapper.update_status(0, ShardStatus.ACTIVE)
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 16)
        for i in range(4):
            tags = {"__name__": "fi_total", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            vals = np.cumsum(rng.random(120))
            for k in range(120):
                b.add(BASE + k * 5_000, [float(vals[k])], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                       spread_default=0)
        srv = FiloHttpServer(node_name=name)
        # wide co-arrival window so the burst test is not timing-flaky
        srv.insights = WorkloadLedger(node=name, co_window_ms=5_000.0)
        srv.bind_dataset(DatasetBinding("prom", ms, planner))
        ports[name] = srv.start()
        servers[name] = srv
    eps = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
    for name in NODES:
        servers[name].fleet = FleetAggregator(
            name, eps, servers[name]._insights_raw, stale_after_s=300.0)
    yield {"servers": servers, "ports": ports, "eps": eps}
    for srv in servers.values():
        srv.shutdown()


class TestFleetConsole:
    """Method order matters (module-scoped cluster): the exact-merge
    proof runs on the quiesced stream BEFORE the burst adds traffic."""

    def test_1_fleet_equals_exact_merge_of_raw_snapshots(self,
                                                         fleet_cluster):
        ports = fleet_cluster["ports"]
        # a partitioned stream: 18 queries, round-robin across nodes,
        # mixing fingerprints and tenants-of-one-shape
        queries = []
        for i in range(18):
            inst = f"i{i % 4}"
            q = (f'sum(rate(fi_total{{instance="{inst}"}}[1m]))'
                 if i % 3 else f'fi_total{{instance="{inst}"}}')
            queries.append(q)
        for i, q in enumerate(queries):
            node = NODES[i % len(NODES)]
            code, body = _query(ports[node], q)
            assert code == 200, body
        raws = {}
        for n in NODES:
            code, body = _get(ports[n], "/admin/insights", raw="true")
            assert code == 200
            raws[n] = body["data"]
            assert raws[n]["node"] == n
        expected = merge_snapshots([raws[n]["insights"] for n in NODES])
        # every issued query is attributed exactly once, fleet-wide
        assert sum(e["count"]
                   for e in expected["fingerprints"].values()) == 18
        assert expected["nodes"] == sorted(NODES)
        for vantage in NODES:
            code, fleet = _get(ports[vantage], "/admin/fleet",
                               refresh="true")
            assert code == 200
            data = fleet["data"]
            # THE acceptance assertion: the one-pane console is the
            # EXACT merge — same ints, same keys, no tolerance
            assert data["insights"] == expected
            assert data["node"] == vantage
            for n in NODES:
                assert data["nodes"][n]["ok"] is True
            assert set(data["replicas"]) == set(NODES)
            for n in NODES:
                assert data["replicas"][n]["prom"]["shards"] == 1

    def test_2_batching_headroom_on_shape_identical_burst(self,
                                                          fleet_cluster):
        ports = fleet_cluster["ports"]
        # shape-identical burst: same range/step/family, different
        # label filters -> same batch key, distinct fingerprints
        errs = []

        def fire(i):
            code, body = _query(ports["fi-a"],
                                f'fi_total{{instance="i{i % 4}"}}')
            if code != 200:
                errs.append(body)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        code, body = _get(ports["fi-a"], "/admin/insights")
        assert code == 200
        data = body["data"]
        assert data["batching"]["headroom"] > 1
        peak_keys = [r for r in data["batching"]["keys"] if r["peak"] > 1]
        assert peak_keys, data["batching"]
        assert peak_keys[0]["batch_key"].startswith("prom|")

    def test_3_unreachable_peer_marked_stale_not_fatal(self,
                                                       fleet_cluster):
        srv = fleet_cluster["servers"]["fi-a"]
        agg = FleetAggregator(
            "fi-a", {"ghost": "http://127.0.0.1:9"},  # nothing listens
            srv._insights_raw, timeout_s=0.5)
        tree = agg.tree(refresh=True)
        assert tree["nodes"]["ghost"]["ok"] is False
        assert tree["nodes"]["ghost"]["error"]
        # the view itself still serves, from the local bundle
        assert tree["nodes"]["fi-a"]["ok"] is True
        assert tree["insights"]["nodes"] == ["fi-a"]


class TestSloBurnAlertLifecycle:
    def test_latency_fault_drives_fast_burn_inactive_pending_firing(
            self, tmp_path):
        # the "injected latency fault": a 1us latency threshold every
        # real query breaches, against a 99.9% availability target —
        # burn = (1.0 bad fraction) / 0.001 budget = 1000x >> 14.4
        config = {
            "node": "slo-node",
            "data-dir": str(tmp_path),
            "datasets": [{"name": "prom", "num-shards": 1,
                          "min-num-nodes": 1, "schema": "gauge",
                          "spread": 0}],
            "dataplane": {
                "watermark-sample-interval-s": 3600,
                "self-scrape": {"enabled": True, "interval-s": 0.15,
                                "dataset": "_system"},
            },
            "insights": {
                "slo": {"objectives": [
                    {"name": "gold", "tenant": "*",
                     "latency-threshold-s": 0.000001,
                     "availability-target": 0.999}],
                    "fast-window-s": 60, "slow-window-s": 120},
            },
            "rules": {
                "self-monitoring": {"enabled": False},
                "slo-burn": {"interval": "200ms", "for": "600ms"},
            },
        }
        srv = FiloServer(config)
        port = srv.start()
        try:
            # the slo-burn pack loaded; alert starts inactive
            code, body = _get(port, "/api/v1/rules")
            assert code == 200
            groups = {g["name"]: g for g in body["data"]["groups"]}
            assert "filodb-slo-burn" in groups
            fast = next(r for r in groups["filodb-slo-burn"]["rules"]
                        if r["name"] == "FiloTenantSLOFastBurn")
            assert fast["state"] == "inactive"
            code, body = _get(port, "/api/v1/alerts")
            assert body["data"]["alerts"] == []

            # breach traffic: every query exceeds the 1us threshold
            for _ in range(10):
                code, _b = _query(port, "up")
                assert code == 200

            # burn is live in the exported filodb_slo_* families
            code, body = _get(port, "/admin/insights")
            (row,) = body["data"]["slo"]
            assert row["objective"] == "gold"
            assert row["fast_burn"] > 14.4
            metrics = _get_text(port, "/metrics")
            line = next(
                ln for ln in metrics.splitlines()
                if ln.startswith("filodb_slo_fast_burn")
                and 'objective="gold"' in ln)
            assert float(line.rsplit(" ", 1)[1]) > 14.4

            # lifecycle: inactive -> pending -> firing, observed
            # through the same /api/v1/alerts surface operators use
            states = set()

            def burn_states(want):
                code, body = _get(port, "/api/v1/alerts")
                for a in body["data"]["alerts"]:
                    if a["labels"].get("alertname") == \
                            "FiloTenantSLOFastBurn":
                        states.add(a["state"])
                return want in states

            _wait(lambda: burn_states("pending"), 20,
                  "fast-burn alert pending")
            _wait(lambda: burn_states("firing"), 20,
                  "fast-burn alert firing")
            assert {"pending", "firing"} <= states
        finally:
            srv.shutdown()
