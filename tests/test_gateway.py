"""Gateway/ingest edge: Influx line protocol, sharding publisher, live TCP
gateway, data producers, CSV source.

Mirrors the reference's gateway specs (reference:
gateway/src/test/.../InfluxProtocolParserSpec.scala — escapes, field
types, timestamps; GatewayServer sharding via ShardMapper+spread).
"""

import socket
import time

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.gateway.influx import (InfluxParseError, parse_line,
                                       parse_lines, to_prom_samples)
from filodb_tpu.gateway.producer import (TestTimeseriesProducer,
                                         csv_stream_elements, series_tags)
from filodb_tpu.gateway.server import GatewayServer, ShardingPublisher
from filodb_tpu.ingest.stream import QueueStreamFactory
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper

BASE = 1_700_000_000_000


class TestInfluxParser:
    def test_basic_line(self):
        r = parse_line("cpu,host=h1,dc=east usage=0.75 1700000000000000000")
        assert r.measurement == "cpu"
        assert r.tags == {"host": "h1", "dc": "east"}
        assert r.fields == {"usage": 0.75}
        assert r.timestamp_ms == 1_700_000_000_000

    def test_multiple_fields(self):
        r = parse_line("mem used=10,free=20.5,cached=3i 1700000000000000000")
        assert r.fields == {"used": 10.0, "free": 20.5, "cached": 3.0}

    def test_escapes(self):
        r = parse_line(r"my\,metric,tag\ one=va\=lue value=1 1700000000000000000")
        assert r.measurement == "my,metric"
        assert r.tags == {"tag one": "va=lue"}

    def test_escaped_equals_in_tag_key(self):
        r = parse_line(r"m,a\=b=c value=1 1700000000000000000")
        assert r.tags == {"a=b": "c"}

    def test_bool_and_string_fields(self):
        r = parse_line('up,host=a ok=true,msg="hello world",v=2 1700000000000000000')
        assert r.fields == {"ok": 1.0, "v": 2.0}  # strings skipped

    def test_no_timestamp_uses_now(self):
        before = int(time.time() * 1000)
        r = parse_line("cpu value=1")
        assert r.timestamp_ms >= before

    def test_blank_and_comment(self):
        assert parse_line("") is None
        assert parse_line("# a comment") is None

    def test_errors(self):
        with pytest.raises(InfluxParseError):
            parse_line("nofields")
        with pytest.raises(InfluxParseError):
            parse_line("m val=abc 123")
        with pytest.raises(InfluxParseError):
            parse_line('m msg="only-string" 123')
        with pytest.raises(InfluxParseError):
            parse_line("cpu value=1 12x3")  # malformed trailing timestamp

    def test_fast_paths_match_general_parser(self):
        """parse_lines_fast (columnar or loop) must be observably
        identical to the per-line parser on every shape it serves, and
        must ROUTE (not break) on shapes it doesn't."""
        from filodb_tpu.gateway.influx import parse_lines_fast

        cases = [
            # columnar-eligible: single field, trailing ts, repeats
            "\n".join(f"cpu,host=h{i % 3},dc=east usage={i * 0.5} "
                      f"17000000000000000{i:02d}" for i in range(40)),
            # mixed field names + negative/exponent values
            ("m,a=1 value=-1.5e-3 1700000000000000000\n"
             "m,a=1 other=2.25 1700000000000001000\n"
             "m2 value=7 1700000000000002000"),
            # loop path: multi-field, int/bool suffixes, blank/comment
            ("mem used=10,free=20.5,cached=3i 1700000000000000000\n"
             "\n# comment\n"
             "up,host=a ok=true,bad=f 1700000000000000000"),
            # slow path: escapes and quoted strings
            (r"my\,metric,tag\ one=va\=lue value=1 1700000000000000000"
             + "\n"
             + 'up,host=a ok=true,msg="x y",v=2 1700000000000000000'),
            # missing timestamp (time.time fallback: compare fields only)
        ]
        for text in cases:
            slow = list(parse_lines(text))
            fast = parse_lines_fast(text)
            assert len(fast) == len(slow), text
            for a, b in zip(fast, slow):
                assert a.measurement == b.measurement
                assert a.tags == b.tags
                assert a.fields == b.fields
                assert a.timestamp_ms == b.timestamp_ms

    def test_columnar_parse_shapes(self):
        from filodb_tpu.gateway.influx import parse_batch_columns

        text = ("cpu,host=a value=1.5 1700000000000000000\n"
                "cpu,host=b value=2.5 1700000000001000000\n"
                "cpu,host=a value=3.5 1700000000002000000\n")
        heads, inv, ufn, finv, vals, ts = parse_batch_columns(text)
        assert len(heads) == 2 and list(vals) == [1.5, 2.5, 3.5]
        assert heads[inv[0]] == heads[inv[2]] == "cpu,host=a"
        assert list(ts) == [1700000000000, 1700000000001,
                            1700000000002]
        # ineligible shapes -> None (never wrong, only absent)
        for bad in ("cpu value=1",                      # no timestamp
                    "cpu a=1,b=2 123",                  # multi-field
                    "cpu value=3i 123",                 # int suffix
                    'cpu msg="x" 123',                  # quoted
                    r"c\,pu value=1 123",               # escape
                    "# only a comment"):
            assert parse_batch_columns(bad) is None, bad

    def test_columnar_batch_memo_detects_change(self):
        """The steady-state head memo must only short-circuit on a
        byte-identical head region — a changed series set re-resolves."""
        from filodb_tpu.gateway.influx import parse_batch_columns

        memo: dict = {}
        t1 = ("cpu,host=a value=1 100000000\n"
              "cpu,host=b value=2 100000000\n")
        h1, inv1, *_ = parse_batch_columns(t1, memo)
        h2, inv2, *_ = parse_batch_columns(
            t1.replace("value=1", "value=9"), memo)
        assert h2 == h1 and list(inv2) == list(inv1)   # memo hit
        t2 = ("cpu,host=a value=1 100000000\n"
              "cpu,host=c value=2 100000000\n")
        h3, inv3, *_ = parse_batch_columns(t2, memo)
        assert "cpu,host=c" in h3                      # re-resolved

    def test_columnar_head_hash_collision_falls_back(self, monkeypatch):
        """Regression (round-4 ADVICE): two DIFFERENT heads whose 128-bit
        positional hashes collide must never be silently merged — the
        byte-verification pass detects the collision and the batch falls
        back to the per-line parser, which stays correct."""
        import numpy as np

        from filodb_tpu.gateway import influx

        # degenerate weight tables: hash = byte sum, so permuted heads
        # ("cpu,host=ab" vs "cpu,host=ba") collide in BOTH streams
        n = 4096
        monkeypatch.setattr(influx, "_HASH_POWS",
                            (np.ones(n, np.uint64), np.ones(n, np.uint64)))
        text = ("cpu,host=ab value=1.5 100000000\n"
                "cpu,host=ba value=2.5 100000000\n")
        assert influx.parse_batch_columns(text) is None
        recs = influx.parse_lines_fast(text)
        assert {r.tags["host"] for r in recs} == {"ab", "ba"}
        # equal heads under the degenerate hash still parse columnar
        ok = ("cpu,host=ab value=1.5 100000000\n"
              "cpu,host=ab value=2.5 200000000\n")
        got = influx.parse_batch_columns(ok)
        assert got is not None and got[0] == ["cpu,host=ab"]

    def test_columnar_native_and_numpy_heads_equivalent(self, monkeypatch):
        """The C head helpers (gather_ranges / head_hash128 /
        verify_heads) must resolve bit-identically to the numpy
        formulation — heads, inverse, field names, memo behavior."""
        import numpy as np

        from filodb_tpu import native
        from filodb_tpu.gateway.influx import parse_batch_columns

        if native.influx_parser() is None:
            pytest.skip("native disabled")
        texts = ["\n".join(
            f"m{i % 3},host=h{i % 17},dc=d{i % 2} "
            f"value={i * 0.5 + b} {100000000 + i * 1000}"
            for i in range(200)) + "\n" for b in range(3)]
        memo_n: dict = {}
        got_native = [parse_batch_columns(t, memo_n) for t in texts]
        monkeypatch.setattr(native, "influx_parser", lambda: None)
        memo_p: dict = {}
        got_numpy = [parse_batch_columns(t, memo_p) for t in texts]
        for gn, gp in zip(got_native, got_numpy):
            assert gn is not None and gp is not None
            assert gn[0] == gp[0]                      # heads
            assert np.array_equal(gn[1], gp[1])        # inverse
            assert gn[2] == gp[2]                      # field names
            assert np.array_equal(gn[3], gp[3])
            assert np.array_equal(gn[4], gp[4])        # values
            assert np.array_equal(gn[5], gp[5])        # timestamps

    def test_columnar_ingest_bad_head_skips_only_its_lines(self):
        """A malformed head mid-batch must drop only ITS lines (counted
        as parse errors); every other series still lands — matching the
        per-line ingest semantics."""
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        from filodb_tpu.gateway.server import ShardingPublisher

        published = []
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], ShardMapper(4),
                                publish=lambda s, c: published.append(c))
        good = "\n".join(f"cpu,host=h{i} value={i} 17000000000000000{i:02d}"
                         for i in range(10))
        batch = good + "\n,bad=x value=99 1700000000000000000"
        n = pub.ingest_influx_batch(batch)
        assert n == 10
        assert pub.parse_errors == 1
        assert pub.samples_in == 10
        assert pub.flush() > 0

    def test_parse_lines_stream(self):
        text = "cpu value=1 1000000\n\n# c\nmem value=2 2000000\n"
        recs = list(parse_lines(text))
        assert [r.measurement for r in recs] == ["cpu", "mem"]

    def test_histogram_kind(self):
        r = parse_line("lat,host=a sum=10,count=5,2=1,4=3,8=5 1000000")
        assert r.kind() == "histogram"
        assert parse_line("lat v=1 1000000").kind() == "gauge"

    def test_to_prom_samples_naming(self):
        r = parse_line("cpu,host=a value=1,idle=2 1000000")
        named = {m: (t, v) for m, t, v in to_prom_samples(r)}
        assert set(named) == {"cpu", "cpu_idle"}
        assert named["cpu"][0]["host"] == "a"


class TestShardingPublisher:
    def test_routes_like_planner_expects(self):
        """Samples published per shard must land on the shard the query
        planner will prune to (the bit-splice contract)."""
        mapper = ShardMapper(8)
        published = {}
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], mapper,
                                lambda s, c: published.setdefault(s, []).append(c),
                                spread=1)
        n_series = 20
        for i in range(n_series):
            tags = series_tags("gw_metric", i)
            name = tags.pop("__name__")
            pub.add_sample(name, tags, BASE + 1000, float(i))
        pub.flush()
        assert pub.samples_in == n_series
        # decode everything back: each record must be on its computed shard
        opts = DatasetOptions()
        total = 0
        for shard, containers in published.items():
            for c in containers:
                for rec in decode_container(c, DEFAULT_SCHEMAS):
                    expect = mapper.ingestion_shard(rec.shard_hash,
                                                    rec.part_hash, 1) % 8
                    assert expect == shard
                    total += 1
        assert total == n_series

    def test_batch_plan_path_matches_per_line_ingest(self):
        """Repeat columnar batches take the memoized PLAN path (second
        batch onward); the decoded records must be identical to per-line
        ingestion of the same payload — hashes, partkeys, shards,
        timestamps, values."""
        def batch(b):
            lines = []
            for i in range(60):
                lines.append(
                    f"cpu,host=h{i % 7},_ws_=demo,_ns_=App-{i % 3} "
                    f"value={i * 0.5 + b} {1_700_000_000_000_000_000 + b * 10**9 + i}")
            return "\n".join(lines)

        def collect(ingest):
            mapper = ShardMapper(8)
            got = {}
            pub = ShardingPublisher(
                DEFAULT_SCHEMAS["gauge"], mapper,
                lambda s, c: got.setdefault(s, []).append(c), spread=2)
            for b in range(3):
                ingest(pub, batch(b))
            pub.flush()
            recs = {}
            for shard, cs in got.items():
                for c in cs:
                    for r in decode_container(c, DEFAULT_SCHEMAS):
                        recs[(shard, r.partkey(), r.timestamp)] = (
                            r.shard_hash, r.part_hash, r.values)
            return recs

        fast = collect(lambda p, t: p.ingest_influx_batch(t))
        slow = collect(lambda p, t: [p.ingest_influx_line(ln + "\n")
                                     for ln in t.splitlines()])
        assert fast.keys() == slow.keys() and fast
        for k, (sh, ph, vals) in slow.items():
            assert fast[k] == (sh, ph, vals), k

    def test_influx_line_ingest(self):
        mapper = ShardMapper(4)
        factory = QueueStreamFactory()
        pub = ShardingPublisher(
            DEFAULT_SCHEMAS["gauge"], mapper,
            lambda s, c: factory.stream_for("ds", s).push(c))
        n = pub.ingest_influx_line(
            "cpu,_ws_=demo,_ns_=App-0,host=h1 value=0.5 1700000000000000000")
        assert n == 1
        assert pub.ingest_influx_line("# comment") == 0
        assert pub.ingest_influx_line("garbage") == 0
        assert pub.parse_errors == 1


class TestGatewayEndToEnd:
    def test_tcp_influx_to_queryable_store(self):
        """Influx lines over TCP -> gateway -> queue streams -> memstore ->
        index lookup, the reference's full edge path."""
        num_shards = 4
        mapper = ShardMapper(num_shards)
        factory = QueueStreamFactory()
        ms = TimeSeriesMemStore()
        for s in range(num_shards):
            ms.setup("ds", DEFAULT_SCHEMAS, s)

        pub = ShardingPublisher(
            DEFAULT_SCHEMAS["gauge"], mapper,
            lambda s, c: factory.stream_for("ds", s).push(c),
            spread=1)
        gw = GatewayServer(pub, flush_every=16)
        port = gw.start()

        producer = TestTimeseriesProducer(DEFAULT_SCHEMAS)
        lines = producer.influx_lines(n_series=6, n_samples=10)
        with socket.create_connection(("127.0.0.1", port), timeout=10) as sk:
            sk.sendall(("\n".join(lines) + "\n").encode())
        # drain the queues into the shards
        deadline = time.time() + 10
        total = 0
        while time.time() < deadline and total < 60:
            total = 0
            for s in range(num_shards):
                st = factory.stream_for("ds", s)
                while not st._q.empty():
                    off, c = st._q.get_nowait()
                    ms.ingest("ds", s, c, offset=off)
                total += ms.get_shard("ds", s).stats.rows_ingested
            time.sleep(0.05)
        gw.shutdown()
        assert total == 60
        # the data is queryable by tag across shards
        found = 0
        for s in range(num_shards):
            res = ms.get_shard("ds", s).lookup_partitions(
                [ColumnFilter("_metric_", Equals("cpu_usage"))], 0, 2**62)
            found += len(res.part_ids)
        assert found == 6


class TestProducers:
    def test_gauge_counter_hist_containers_decode(self):
        p = TestTimeseriesProducer(DEFAULT_SCHEMAS)
        for containers, schema in [
                (p.gauge_containers(n_series=3, n_samples=5), "gauge"),
                (p.counter_containers(n_series=3, n_samples=5), "prom-counter"),
                (p.histogram_containers(n_series=2, n_samples=4),
                 "prom-histogram")]:
            n = 0
            for c in containers:
                for rec in decode_container(c, DEFAULT_SCHEMAS):
                    assert rec.schema_hash == DEFAULT_SCHEMAS[schema].schema_hash
                    n += 1
            assert n > 0

    def test_counter_monotone(self):
        p = TestTimeseriesProducer(DEFAULT_SCHEMAS)
        recs = [r for c in p.counter_containers(n_series=1, n_samples=20)
                for r in decode_container(c, DEFAULT_SCHEMAS)]
        vals = [r.values[0] for r in recs]
        assert vals == sorted(vals)

    def test_hist_ingests_into_store(self):
        p = TestTimeseriesProducer(DEFAULT_SCHEMAS)
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        for off, c in enumerate(p.histogram_containers(n_series=2, n_samples=5)):
            ms.ingest("ds", 0, c, offset=off)
        sh = ms.get_shard("ds", 0)
        assert sh.stats.rows_ingested == 10
        res = sh.lookup_partitions(
            [ColumnFilter("_metric_", Equals("request_latency"))], 0, 2**62)
        tags_list, batch = sh.scan_batch(res.part_ids, 0, 2**62)
        assert batch.hist is not None
        assert batch.hist.shape[2] == 8  # buckets


class TestCsvSource:
    def test_csv_elements_roundtrip(self):
        text = ("timestamp,value,metric,host,_ws_,_ns_\n"
                f"{BASE + 1000},1.5,disk_io,h1,demo,ns\n"
                f"{BASE + 2000},2.5,disk_io,h1,demo,ns\n"
                f"{BASE + 3000},3.5,disk_io,h2,demo,ns\n")
        elements = csv_stream_elements(
            text, DEFAULT_SCHEMAS, "gauge",
            tag_columns=["metric", "host", "_ws_", "_ns_"],
            value_columns=["value"])
        assert len(elements) >= 1
        recs = [r for _, c in elements
                for r in decode_container(c, DEFAULT_SCHEMAS)]
        assert len(recs) == 3
        assert recs[0].values == (1.5,)
        assert recs[2].tags["host"] == "h2"
