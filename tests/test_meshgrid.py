"""Resident grid x mesh serving (parallel/meshgrid.py): the SPMD program
over per-shard HBM-resident plans must be observably identical to the
per-shard scatter-gather path, must actually TAKE the resident path, and
must move zero bytes host->device on a repeat query (reference semantics:
BlockManager.scala:142 resident serving x SingleClusterPlanner.scala:
223-258 scatter-gather).

Runs on the 8-device virtual CPU mesh from tests/conftest.py.
"""

import numpy as np
import pytest

import jax

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, partition_hash, \
    shard_key_hash
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel import meshgrid
from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext

BASE = 1_700_000_000_000
NUM_SHARDS = 4
N_SERIES = 24
N_ROWS = 120
STEP = 10_000


def _load(num_shards=NUM_SHARDS, n_series=N_SERIES, jitter_shards=(),
          seed=11, metric="mm"):
    """Regular 10s cadence (grid-eligible, uniform phase).  Shards in
    ``jitter_shards`` get per-sample in-bucket jitter: still dense and
    one-sample-per-bucket, but NOT uniform-phase — the dense/phase MEET
    path."""
    ms = TimeSeriesMemStore()
    opts = DatasetOptions()
    mapper = ShardMapper(num_shards)
    for s in range(num_shards):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(seed)
    for i in range(n_series):
        tags = {"_metric_": metric, "inst": f"i{i}", "grp": f"g{i % 3}",
                "_ws_": "w", "_ns_": "n"}
        shard = mapper.ingestion_shard(shard_key_hash(tags, opts),
                                       partition_hash(tags, opts),
                                       2) % num_shards
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = BASE + np.arange(N_ROWS) * STEP
        if shard in jitter_shards:
            ts = ts + rng.integers(1, STEP - 1, size=N_ROWS)
        vals = np.cumsum(rng.random(N_ROWS))
        b.add_series(ts.tolist(), [vals.tolist()], tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", shard).ingest_container(c, off)
    return ms, mapper


def _planner(mapper, engine=None):
    provider = (lambda: engine) if engine is not None else None
    return SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                spread_default=2,
                                mesh_engine_provider=provider)


def _run(planner, ms, promql, start, end, step=30_000):
    plan = query_range_to_logical_plan(promql, start, step, end)
    ep = planner.materialize(plan, QueryContext())
    result = ep.execute(ExecContext(ms, QueryContext()))
    out = {}
    for b in result.batches:
        for tags, ts, vals in b.to_series():
            out[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                np.asarray(vals))
    return out


def _assert_equiv(fused, plain):
    assert set(fused) == set(plain) and plain
    for k in plain:
        np.testing.assert_array_equal(fused[k][0], plain[k][0])
        np.testing.assert_allclose(fused[k][1], plain[k][1],
                                   rtol=1e-6, atol=1e-9,
                                   equal_nan=True, err_msg=str(k))


START = BASE + 300_000
END = BASE + 900_000

QUERIES = [
    'sum(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'sum by (grp)(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'count(mm{_ws_="w",_ns_="n"})',
    'avg by (grp)(sum_over_time(mm{_ws_="w",_ns_="n"}[1m]))',
    'max(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'min by (grp)(mm{_ws_="w",_ns_="n"})',
    'sum by (grp)(increase(mm{_ws_="w",_ns_="n"}[2m]))',
    # round 5 (VERDICT r4 #2): the non-distributive moment family
    'stddev by (grp)(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'stdvar(mm{_ws_="w",_ns_="n"})',
    'group by (grp)(mm{_ws_="w",_ns_="n"})',
]

# k-slot / member ops: exact equivalence (k-heap merge and value counts
# are lossless); quantile is sketch-accurate and tested separately
K_MEMBER_QUERIES = [
    'topk(3, rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'bottomk(2, mm{_ws_="w",_ns_="n"})',
    'topk by (grp)(2, mm{_ws_="w",_ns_="n"})',
    'count_values("v", mm{_ws_="w",_ns_="n"})',
    'count_values by (grp)("v", mm{_ws_="w",_ns_="n"})',
]

# one representative per family for the zero-upload repeat contract
REPEAT_QUERIES = [
    'sum by (grp)(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'stddev by (grp)(mm{_ws_="w",_ns_="n"})',
    'topk(3, rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'quantile(0.9, mm{_ws_="w",_ns_="n"})',
    'count_values("v", mm{_ws_="w",_ns_="n"})',
]


class TestResidentGridMesh:
    @pytest.mark.parametrize("promql", QUERIES)
    def test_equivalent_and_resident(self, promql):
        ms, mapper = _load()
        engine = MeshEngine(make_mesh())
        plain = _run(_planner(mapper), ms, promql, START, END)
        before = dict(meshgrid.STATS)
        fused = _run(_planner(mapper, engine), ms, promql, START, END)
        _assert_equiv(fused, plain)
        assert meshgrid.STATS["serves"] > before["serves"], \
            "resident grid-mesh path was not taken"

    @pytest.mark.parametrize("promql", K_MEMBER_QUERIES)
    def test_k_member_ops_equivalent_and_resident(self, promql):
        """topk/bottomk/count_values over resident lanes: lossless, so
        exact equivalence with the per-shard path — and the resident
        program must actually run."""
        ms, mapper = _load()
        engine = MeshEngine(make_mesh())
        plain = _run(_planner(mapper), ms, promql, START, END)
        before = dict(meshgrid.STATS)
        fused = _run(_planner(mapper, engine), ms, promql, START, END)
        _assert_equiv(fused, plain)
        assert meshgrid.STATS["serves"] > before["serves"], \
            "resident grid-mesh path was not taken"

    def test_quantile_resident_close_to_exact(self):
        """quantile over resident lanes is a t-digest sketch; the
        per-shard path is exact at this cardinality — sketch accuracy,
        same keys, same NaN shape, resident program taken."""
        ms, mapper = _load()
        engine = MeshEngine(make_mesh())
        for promql in ('quantile(0.9, mm{_ws_="w",_ns_="n"})',
                       'quantile by (grp)(0.5, rate(mm{_ws_="w",'
                       '_ns_="n"}[2m]))'):
            plain = _run(_planner(mapper), ms, promql, START, END)
            before = dict(meshgrid.STATS)
            fused = _run(_planner(mapper, engine), ms, promql, START, END)
            assert meshgrid.STATS["serves"] > before["serves"], promql
            assert set(fused) == set(plain) and plain, promql
            for k in plain:
                pv, fv = plain[k][1], fused[k][1]
                assert (np.isfinite(pv) == np.isfinite(fv)).all(), k
                fin = np.isfinite(pv)
                np.testing.assert_allclose(fv[fin], pv[fin], rtol=0.08,
                                           err_msg=f"{promql} {k}")

    @pytest.mark.parametrize("promql", REPEAT_QUERIES)
    def test_repeat_query_zero_host_upload(self, monkeypatch, promql):
        """The dashboard-refresh contract for EVERY aggregator family: a
        repeat query hits the assembly memo and performs NO host->device
        transfer at all."""
        ms, mapper = _load()
        engine = MeshEngine(make_mesh())
        planner = _planner(mapper, engine)
        first = _run(planner, ms, promql, START, END)
        before = dict(meshgrid.STATS)
        uploads = []
        real_put = jax.device_put

        def spy(x, *a, **kw):
            if isinstance(x, np.ndarray):
                uploads.append(x.nbytes)
            return real_put(x, *a, **kw)

        monkeypatch.setattr(jax, "device_put", spy)
        second = _run(planner, ms, promql, START, END)
        monkeypatch.undo()
        assert meshgrid.STATS["memo_hits"] > before["memo_hits"], \
            "repeat query re-assembled the mesh inputs"
        assert meshgrid.STATS["serves"] > before["serves"]
        assert uploads == [], \
            f"repeat query uploaded {sum(uploads)} bytes host->device"
        if "quantile" in promql:
            assert set(second) == set(first)
        else:
            _assert_equiv(second, first)

    def test_op_switch_reuses_assembly(self):
        """The assembled residents are op-independent: a dashboard
        switching sum -> topk -> stddev on the same selector re-uses the
        assembly (memo hit), compiling only the new program."""
        ms, mapper = _load()
        engine = MeshEngine(make_mesh())
        planner = _planner(mapper, engine)
        _run(planner, ms, QUERIES[1], START, END)
        before = dict(meshgrid.STATS)
        # same selector, same grouping (the garr layout is part of the
        # assembly): only the aggregator program changes
        _run(planner, ms, 'topk by (grp)(2, rate(mm{_ws_="w",_ns_="n"}'
                          '[2m]))', START, END)
        _run(planner, ms, 'stddev by (grp)(rate(mm{_ws_="w",_ns_="n"}'
                          '[2m]))', START, END)
        assert meshgrid.STATS["assembles"] == before["assembles"], \
            "op switch re-assembled the residents"
        assert meshgrid.STATS["memo_hits"] >= before["memo_hits"] + 2

    def test_filler_slices_shards_not_multiple_of_devices(self):
        """4 shards over the 8-device mesh: 4 filler slices must not
        perturb results (NaN lanes drop into the spare bucket)."""
        assert len(jax.devices()) == 8
        ms, mapper = _load(num_shards=4)
        engine = MeshEngine(make_mesh())
        plain = _run(_planner(mapper), ms, QUERIES[0], START, END)
        before = meshgrid.STATS["serves"]
        fused = _run(_planner(mapper, engine), ms, QUERIES[0], START, END)
        assert meshgrid.STATS["serves"] > before
        _assert_equiv(fused, plain)

    def test_multiple_plans_per_device(self):
        """A 2-device mesh with 4+ shards: ksub > 1 exercises the local
        accumulation loop and uneven per-device slice counts."""
        engine = MeshEngine(make_mesh(jax.devices()[:2]))
        ms, mapper = _load(num_shards=8, n_series=40)
        plain = _run(_planner(mapper), ms, QUERIES[1], START, END)
        before = meshgrid.STATS["serves"]
        fused = _run(_planner(mapper, engine), ms, QUERIES[1], START, END)
        assert meshgrid.STATS["serves"] > before
        _assert_equiv(fused, plain)

    def test_mixed_dense_phase_meet(self):
        """One shard uniform-phase, others jittered: the program must
        MEET to ts mode and stay correct."""
        ms, mapper = _load(jitter_shards=(1, 2))
        engine = MeshEngine(make_mesh())
        for promql in (QUERIES[0], QUERIES[6]):
            plain = _run(_planner(mapper), ms, promql, START, END)
            before = meshgrid.STATS["serves"]
            fused = _run(_planner(mapper, engine), ms, promql, START, END)
            assert meshgrid.STATS["serves"] > before
            _assert_equiv(fused, plain)

    def test_grid_ineligible_shard_falls_back_per_shard(self):
        """A shard whose cadence defeats the grid (two samples per
        bucket) must be served by the host-batch mesh path while the
        others stay resident — results identical, nothing dropped."""
        ms, mapper = _load()
        # shard 0: extra series at 5s cadence -> two samples per 10s
        # bucket -> grid disabled for that shard
        opts = DatasetOptions()
        tags = {"_metric_": "mm", "inst": "odd", "grp": "g0",
                "_ws_": "w", "_ns_": "n"}
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = BASE + np.arange(2 * N_ROWS) * (STEP // 2)
        b.add_series(ts.tolist(), [np.cumsum(
            np.ones(2 * N_ROWS)).tolist()], tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", 0).ingest_container(c, off)
        engine = MeshEngine(make_mesh())
        plain = _run(_planner(mapper), ms, QUERIES[0], START, END)
        fused = _run(_planner(mapper, engine), ms, QUERIES[0], START, END)
        _assert_equiv(fused, plain)

    def test_unsupported_layout_still_correct(self):
        """An op whose layout defeats the resident composition (stddev
        over per-sample-jittered shards MEETs to ts mode; a shard with
        two samples per bucket defeats the grid entirely) must still be
        served correctly via fallback."""
        ms, mapper = _load(jitter_shards=(0, 1, 2, 3))
        engine = MeshEngine(make_mesh())
        promql = 'stddev(mm{_ws_="w",_ns_="n"})'
        plain = _run(_planner(mapper), ms, promql, START, END)
        fused = _run(_planner(mapper, engine), ms, promql, START, END)
        _assert_equiv(fused, plain)

    def test_histogram_shards_serve_resident(self):
        """First-class histogram sums run in the RESIDENT grid x mesh
        program (bucket lanes + psum over group*bucket slots),
        identical to the per-shard path."""
        from tests.data import START_TS, histogram_containers

        ms2 = TimeSeriesMemStore()
        mapper = ShardMapper(4)
        for s in range(4):
            ms2.setup("prom", DEFAULT_SCHEMAS, s)
        for shard_num in (0, 1, 2):
            for off, c in enumerate(histogram_containers(
                    n_series=2, n_samples=60, metric="hgm",
                    seed=shard_num)):
                ms2.get_shard("prom", shard_num).ingest_container(c, off)
        engine = MeshEngine(make_mesh())
        # start past the bare selector's 5m staleness lookback so the
        # resident plan's first window lands inside the staged grid
        start, end = START_TS + 320_000, START_TS + 500_000
        for promql in ('sum(rate(hgm{_ws_="demo",_ns_="App-0"}[2m]))',
                       'sum(hgm{_ws_="demo",_ns_="App-0"})'):
            plain = _run(_planner(mapper), ms2, promql, start, end)
            before = meshgrid.STATS["serves"]
            fused = _run(_planner(mapper, engine), ms2, promql,
                         start, end)
            assert meshgrid.STATS["serves"] > before, \
                f"hist query fell off the resident path: {promql}"
            _assert_equiv(fused, plain)

    def test_repin_invalidates_and_rebuilds(self):
        """Blocks built for a single-device planner (default device)
        survive pinning to device 0 but rebuild when re-pinned
        elsewhere; results stay identical throughout."""
        ms, mapper = _load(num_shards=2)
        plain = _run(_planner(mapper), ms, QUERIES[0], START, END)
        shard = ms.get_shard("prom", 0)
        shard.pin_grid_device(jax.devices()[3])
        engine = MeshEngine(make_mesh())
        fused = _run(_planner(mapper, engine), ms, QUERIES[0], START, END)
        _assert_equiv(fused, plain)


class TestCompressedResidentMesh:
    """ISSUE 3: the mesh path over COMPRESSED residents — blocks stay
    packed in HBM, uniform-phase plans never stage a ts plane, and the
    dashboard-refresh contract (memo hit, zero host decode, zero
    re-upload, zero block rebuilds) holds for the compressed form."""

    def _load_counters(self, num_shards=NUM_SHARDS, n_series=N_SERIES):
        """Integer-valued counters (XOR-compressible) on an exact 10s
        cadence with a per-series constant phase — compresses AND proves
        uniform-phase, so plans take the no-ts-plane mesh form."""
        ms = TimeSeriesMemStore()
        opts = DatasetOptions()
        mapper = ShardMapper(num_shards)
        for s in range(num_shards):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
        rng = np.random.default_rng(23)
        for i in range(n_series):
            tags = {"_metric_": "cc", "inst": f"i{i}",
                    "grp": f"g{i % 3}", "_ws_": "w", "_ns_": "n"}
            shard = mapper.ingestion_shard(shard_key_hash(tags, opts),
                                           partition_hash(tags, opts),
                                           2) % num_shards
            b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                              container_size=1 << 20)
            ph = int(rng.integers(1, STEP))
            ts = BASE + np.arange(N_ROWS) * STEP - STEP + ph
            vals = (1_000_000
                    + np.cumsum(rng.integers(-500, 500, N_ROWS))
                    ).astype(np.float64)
            b.add_series(ts.tolist(), [vals.tolist()], tags)
            for off, c in enumerate(b.containers()):
                ms.get_shard("prom", shard).ingest_container(c, off)
        for s in range(num_shards):
            ms.get_shard("prom", s).flush_all()
        return ms, mapper

    def test_compressed_resident_repeat_memo_and_zero_rebuild(
            self, monkeypatch):
        ms, mapper = self._load_counters()
        engine = MeshEngine(make_mesh())
        planner = _planner(mapper, engine)
        promql = 'sum by (grp)(rate(cc{_ws_="w",_ns_="n"}[2m]))'
        plain = _run(_planner(mapper), ms, promql, START, END)
        first = _run(planner, ms, promql, START, END)
        _assert_equiv(first, plain)
        # the residents must actually BE compressed and uniform-phase
        comp_blocks = ts_elided = 0
        builds = 0
        for s in range(NUM_SHARDS):
            shard = ms.get_shard("prom", s)
            for cache in shard.device_caches.values():
                builds += cache.builds
                for blk in cache.blocks.values():
                    comp_blocks += isinstance(blk.vals, dict)
                    ts_elided += blk.ts is None
        assert comp_blocks > 0, "counter data did not pack"
        assert ts_elided > 0, "uniform-phase ts plane was not elided"
        before = dict(meshgrid.STATS)
        uploads = []
        real_put = jax.device_put

        def spy(x, *a, **kw):
            if isinstance(x, np.ndarray):
                uploads.append(x.nbytes)
            return real_put(x, *a, **kw)

        monkeypatch.setattr(jax, "device_put", spy)
        second = _run(planner, ms, promql, START, END)
        monkeypatch.undo()
        _assert_equiv(second, first)
        # repeat query: assembly memo hit, no host decode (no rebuild),
        # no re-upload — the compressed analog of the dense contract
        assert meshgrid.STATS["memo_hits"] > before["memo_hits"], \
            "repeat compressed query re-assembled the mesh inputs"
        assert uploads == [], \
            f"repeat compressed query uploaded {sum(uploads)} bytes"
        builds2 = sum(c.builds for s in range(NUM_SHARDS)
                      for c in ms.get_shard("prom", s)
                      .device_caches.values())
        assert builds2 == builds, "repeat query re-decoded host chunks"

    def test_phase_plans_stage_no_ts_plane(self):
        """Uniform-phase mesh plans carry ts=None — the staged resident
        is the value plane only (half the HBM of the ts-streaming
        form), and the SPMD program ships a 1-row dummy instead."""
        ms, mapper = self._load_counters()
        devices = list(make_mesh().devices.flat)
        plans = []
        for s in range(NUM_SHARDS):
            shard = ms.get_shard("prom", s)
            shard.pin_grid_device(devices[s % len(devices)])
            res = shard.lookup_partitions([], 0, 2**62)
            ids = res.part_ids
            if len(ids) == 0:
                continue
            from filodb_tpu.query.logical import RangeFunctionId as F
            plan = shard.mesh_grid_plan(
                ids, F.RATE, BASE + 300_000, 10, 30_000, 120_000,
                list(range(len(ids))))
            if plan is not None:
                plans.append(plan)
        assert plans, "no shard produced a mesh plan"
        for p in plans:
            assert p.phase is not None
            assert p.ts is None, "phase-mode plan staged a ts plane"
            assert p.vals.shape[0] > 0

    def test_compressed_hist_blocks_serve_through_mesh(self):
        """ISSUE 14: histogram bucket planes stay PACKED at rest and the
        grid x mesh path stages (decodes) them on device — the served
        answer is identical to the per-shard scatter-gather path."""
        from filodb_tpu.codecs import histcodec
        from filodb_tpu.core.histogram import GeometricBuckets

        hb = 8
        ms = TimeSeriesMemStore()
        opts = DatasetOptions()
        mapper = ShardMapper(4)
        for s in range(4):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
        rng = np.random.default_rng(29)
        buckets = GeometricBuckets(2.0, 2.0, hb)
        for i in range(12):
            tags = {"_metric_": "hcc", "inst": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            shard = mapper.ingestion_shard(shard_key_hash(tags, opts),
                                           partition_hash(tags, opts),
                                           2) % 4
            b = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"], opts,
                              container_size=1 << 20)
            ph = int(rng.integers(1, STEP))
            cum = np.zeros(hb, np.int64)
            for t in range(N_ROWS):
                cum += 128 * rng.integers(1, 8, hb)
                vals = 2 ** 23 + np.cumsum(cum)
                blob = histcodec.encode_hist_value(buckets, vals)
                b.add(int(BASE + t * STEP - STEP + ph),
                      (float(vals[-1]), float(vals[-1]), blob), tags)
            for off, c in enumerate(b.containers()):
                ms.get_shard("prom", shard).ingest_container(c, off)
        for s in range(4):
            ms.get_shard("prom", s).flush_all()
        engine = MeshEngine(make_mesh())
        promql = 'sum(rate(hcc{_ws_="w",_ns_="n"}[2m]))'
        plain = _run(_planner(mapper), ms, promql, START, END)
        before = meshgrid.STATS["serves"]
        fused = _run(_planner(mapper, engine), ms, promql, START, END)
        assert meshgrid.STATS["serves"] > before, \
            "compressed hist query fell off the resident mesh path"
        _assert_equiv(fused, plain)
        comp = sum(isinstance(blk.vals, dict)
                   for s in range(4)
                   for cache in ms.get_shard("prom", s)
                   .device_caches.values()
                   for blk in cache.blocks.values())
        assert comp > 0, "hist bucket planes did not pack at rest"
