"""Segment-aggregation kernels vs numpy groupby oracle (model: the
reference's AggrOverRangeVectorsSpec)."""

import numpy as np
import jax.numpy as jnp

from filodb_tpu.ops import aggregate as agg

rng = np.random.default_rng(31)

S, T, G = 40, 12, 5
VALS = rng.normal(10, 5, (S, T))
VALS[rng.random((S, T)) < 0.1] = np.nan
IDS = rng.integers(0, G, S).astype(np.int32)
VJ, IJ = jnp.asarray(VALS), jnp.asarray(IDS)


def oracle_group(op):
    out = np.full((G, T), np.nan)
    for g in range(G):
        rows = VALS[IDS == g]
        for t in range(T):
            col = rows[:, t]
            col = col[np.isfinite(col)]
            if len(col):
                out[g, t] = op(col)
    return out


class TestSegmentAggregators:
    def test_sum(self):
        np.testing.assert_allclose(np.asarray(agg.seg_sum(VJ, IJ, G)),
                                   oracle_group(np.sum), rtol=1e-9, equal_nan=True)

    def test_count(self):
        np.testing.assert_allclose(np.asarray(agg.seg_count(VJ, IJ, G)),
                                   oracle_group(len), equal_nan=True)

    def test_min_max(self):
        np.testing.assert_allclose(np.asarray(agg.seg_min(VJ, IJ, G)),
                                   oracle_group(np.min), equal_nan=True)
        np.testing.assert_allclose(np.asarray(agg.seg_max(VJ, IJ, G)),
                                   oracle_group(np.max), equal_nan=True)

    def test_avg(self):
        np.testing.assert_allclose(np.asarray(agg.seg_avg(VJ, IJ, G)),
                                   oracle_group(np.mean), rtol=1e-9, equal_nan=True)

    def test_stdvar_stddev(self):
        np.testing.assert_allclose(np.asarray(agg.seg_stdvar(VJ, IJ, G)),
                                   oracle_group(np.var), rtol=1e-6, equal_nan=True)
        np.testing.assert_allclose(np.asarray(agg.seg_stddev(VJ, IJ, G)),
                                   oracle_group(np.std), rtol=1e-6, equal_nan=True)

    def test_quantile(self):
        got = np.asarray(agg.seg_quantile(VJ, IJ, G, 0.75))
        np.testing.assert_allclose(got, oracle_group(lambda c: np.quantile(c, 0.75)),
                                   rtol=1e-9, equal_nan=True)

    def test_group_ids(self):
        keys = [("a",), ("b",), ("a",), ("c",), ("b",)]
        ids, uniq = agg.group_ids(keys)
        assert ids.tolist() == [0, 1, 0, 2, 1]
        assert uniq == [("a",), ("b",), ("c",)]

    def test_single_group(self):
        ids = np.zeros(S, dtype=np.int32)
        got = np.asarray(agg.seg_sum(VJ, jnp.asarray(ids), 1))
        expect = np.nansum(VALS, axis=0)
        np.testing.assert_allclose(got[0], expect, rtol=1e-9)


class TestTopK:
    def test_topk_values_and_indices(self):
        k = 3
        vals, idx = agg.seg_topk(VJ, IJ, G, k)
        vals, idx = np.asarray(vals), np.asarray(idx)
        assert vals.shape == (G, k, T) and idx.shape == (G, k, T)
        for g in range(G):
            members = np.nonzero(IDS == g)[0]
            for t in range(T):
                col = VALS[members, t]
                fin = np.isfinite(col)
                expect = np.sort(col[fin])[::-1][:k]
                got = vals[g, :, t]
                got = got[np.isfinite(got)]
                np.testing.assert_allclose(got, expect)
                # indices point at series holding those values
                for r, v in enumerate(got):
                    assert VALS[idx[g, r, t], t] == v
                    assert IDS[idx[g, r, t]] == g

    def test_bottomk(self):
        k = 2
        vals, _ = agg.seg_topk(VJ, IJ, G, k, bottom=True)
        vals = np.asarray(vals)
        for g in range(G):
            col = VALS[IDS == g][:, 0]
            fin = col[np.isfinite(col)]
            expect = np.sort(fin)[:k]
            got = vals[g, :, 0]
            np.testing.assert_allclose(got[np.isfinite(got)], expect)

    def test_k_larger_than_group(self):
        ids = np.zeros(3, dtype=np.int32)
        v = jnp.asarray(rng.normal(0, 1, (3, 2)))
        vals, idx = agg.seg_topk(v, jnp.asarray(ids), 1, 5)
        vals, idx = np.asarray(vals), np.asarray(idx)
        assert np.isnan(vals[0, 3:, :]).all()
        assert (idx[0, 3:, :] == -1).all()


class TestAbsentAndHist:
    def test_absent(self):
        v = np.full((3, 4), np.nan)
        v[1, 2] = 5.0
        out = np.asarray(agg.absent(jnp.asarray(v)))
        assert np.isnan(out[2]) and out[0] == 1.0 and out[1] == 1.0

    def test_hist_sum(self):
        B = 4
        h = rng.random((S, T, B))
        ids = IDS
        got = np.asarray(agg.seg_hist_sum(jnp.asarray(h), IJ, G))
        for g in range(G):
            np.testing.assert_allclose(got[g], h[IDS == g].sum(axis=0), rtol=1e-9)
