"""Data-plane observability (ISSUE 6): cardinality explorer, watermark
ledger, self-scrape, memo eviction, shard-health emission.

The load-bearing assertion is the PR 9-style reconciliation guarantee:
/admin/cardinality totals must match a full part-key-index walk exactly
under concurrent series create/evict/purge, and per-tenant counts must
agree with SeriesQuota occupancy."""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.cardinality import Ewma, build_report
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.watermarks import WatermarkLedger
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.utils.observability import REGISTRY
from filodb_tpu.workload.quota import SeriesQuota

BASE = 1_700_000_000_000
MAX = np.iinfo(np.int64).max


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _one_row_container(tags, ts):
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 16)
    b.add(int(ts), [1.0], tags)
    return list(b.containers())


# ---------------------------------------------------------------------------
# cardinality explorer
# ---------------------------------------------------------------------------


class TestCardinalityReconciliation:
    def test_report_matches_index_walk_under_concurrent_churn(self):
        """The acceptance-criteria e2e: mutators create/evict/purge
        while readers hammer /admin/cardinality; every mid-churn report
        is internally consistent (one atomic snapshot per shard), and
        at quiescence the totals match a full index walk and the
        SeriesQuota occupancy exactly."""
        ms = TimeSeriesMemStore()
        ms.setup("card", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("card", 0)
        quota = SeriesQuota(dataset="card")
        sh.series_quota = quota

        srv = FiloHttpServer()
        srv.bind_dataset(DatasetBinding("card", ms, planner=None,
                                        quota=quota))
        port = srv.start()
        errors: list[str] = []
        stop = threading.Event()

        def mutate():
            off = 0
            for i in range(250):
                # 4 new series per round, 4 tenants
                for k in range(4):
                    tags = {"__name__": "churn_m", "u": f"s{i}_{k}",
                            "_ws_": "w", "_ns_": f"t{(i + k) % 4}"}
                    for c in _one_row_container(tags, BASE + i * 1000):
                        sh.ingest_container(c, off)
                        off += 1
                if i % 9 == 5:
                    # stop the oldest few, then evict them
                    for pid in list(sh.partitions)[:3]:
                        sh.index.update_end_time(pid, BASE + i * 1000)
                    sh.evict_partitions(3)
                if i % 13 == 7:
                    sh.purge_expired(retention_ms=60_000,
                                     now_ms=BASE + i * 1000)

        def read():
            while not stop.is_set():
                code, body = _get(port, "/admin/cardinality",
                                  dataset="card", topk=5)
                if code != 200:
                    errors.append(f"HTTP {code}: {body}")
                    return
                data = body["data"]
                if sum(data["tenants"].values()) \
                        != data["total_active_series"]:
                    errors.append(f"tenant sum != total: {data}")
                    return
                for row in data["shards"]:
                    if sum(row["tenants"].values()) != row["active_series"]:
                        errors.append(f"shard-level mismatch: {row}")
                        return

        readers = [threading.Thread(target=read) for _ in range(3)]
        mt = threading.Thread(target=mutate)
        for t in readers:
            t.start()
        mt.start()
        mt.join()
        stop.set()
        for t in readers:
            t.join()
        srv.shutdown()
        assert not errors, errors

        # quiescent: full index walk (ground truth from the raw tag
        # dicts, NOT the refcounts the report is built on)
        walk_tenants: dict[str, int] = {}
        for pid in list(sh.index._tags):
            tags = sh.index._tags[pid]
            t = tags.get("_ns_", "")
            walk_tenants[t] = walk_tenants.get(t, 0) + 1
        walk_total = len(sh.index._tags)
        assert walk_total > 0
        assert sh.stats.partitions_evicted > 0
        assert sh.stats.partitions_purged > 0

        report = build_report("card", ms.shards("card"), topk=5)
        assert report["total_active_series"] == walk_total
        assert report["tenants"] == walk_tenants
        # per-value label counts agree with a walk over every label
        snap_active, snap_labels = sh.index.cardinality_snapshot()
        walk_labels: dict[str, dict[str, int]] = {}
        for pid in list(sh.index._tags):
            for k, v in sh.index._tags[pid].items():
                walk_labels.setdefault(k, {})
                walk_labels[k][v] = walk_labels[k].get(v, 0) + 1
        assert snap_active == walk_total
        assert snap_labels == walk_labels
        # SeriesQuota occupancy agrees with the report's tenant counts
        assert quota.snapshot()["active"] == walk_tenants

    def test_churn_counters_and_rates(self):
        ms = TimeSeriesMemStore()
        ms.setup("churn2", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("churn2", 0)
        for i in range(10):
            tags = {"__name__": "m", "u": str(i), "_ws_": "w", "_ns_": "n"}
            for c in _one_row_container(tags, BASE + i):
                sh.ingest_container(c, i)
        sh.purge_expired(retention_ms=1, now_ms=BASE + 10_000_000)
        assert sh.cardinality.created_total == 10
        assert sh.cardinality.removed_total == 10
        assert sh.cardinality.create_ewma.rate() > 0
        created = REGISTRY.counter("filodb_index_churn_created_total")
        assert created.value(dataset="churn2", shard=0) == 10
        removed = REGISTRY.counter("filodb_index_churn_removed_total")
        assert removed.value(dataset="churn2", shard=0,
                             reason="purge") == 10
        active = REGISTRY.gauge("filodb_index_cardinality_active_series")
        assert active.value(dataset="churn2", shard=0) == 0

    def test_topk_ranking_and_bounds(self):
        ms = TimeSeriesMemStore()
        ms.setup("rank", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("rank", 0)
        off = 0
        for i in range(12):
            tags = {"__name__": "m", "hi_card": f"v{i}",
                    "lo_card": f"g{i % 2}", "_ws_": "w", "_ns_": "n"}
            for c in _one_row_container(tags, BASE + i):
                sh.ingest_container(c, off)
                off += 1
        report = build_report("rank", ms.shards("rank"), topk=2)
        row = report["shards"][0]
        # hi_card (12 values) must outrank lo_card (2 values)
        assert row["top_labels"][0]["label"] == "hi_card"
        assert row["top_labels"][0]["values"] == 12
        assert len(row["top_labels"]) == 2          # topk bounds labels
        assert len(row["top_labels"][0]["top_values"]) == 2  # and values

    def test_ewma_decays(self):
        e = Ewma(halflife_s=0.05)
        e.note(100)
        r0 = e.rate()
        assert r0 > 0
        time.sleep(0.15)
        assert e.rate() < r0 / 4


# ---------------------------------------------------------------------------
# watermark ledger
# ---------------------------------------------------------------------------


def _ingest_rows(sh, n, start_off=0):
    for i in range(n):
        tags = {"__name__": "wm", "u": str(i), "_ws_": "w", "_ns_": "n"}
        for c in _one_row_container(tags, BASE + i * 1000):
            sh.ingest_container(c, start_off + i)


class TestWatermarkLedger:
    def test_chain_and_lag(self):
        ms = TimeSeriesMemStore()
        ms.setup("wm1", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("wm1", 0)
        _ingest_rows(sh, 20)
        sh.flush_all()
        wm = WatermarkLedger(node="n0")
        wm.watch("wm1", ms, end_offset_fn=lambda s: 25)
        row = wm.sample()["datasets"]["wm1"]["shards"][0]
        assert row["watermarks"]["ingested"] == 19
        assert row["watermarks"]["broker_end"] == 25
        # flush_all checkpoints at latest_offset on every group
        assert row["watermarks"]["flushed"] == 19
        assert row["watermarks"]["checkpoint"] == 19
        assert row["lag"]["rows"] == 5
        assert row["lag"]["seconds"] > 0
        g = REGISTRY.gauge("filodb_ingest_lag_rows")
        assert g.value(dataset="wm1", shard=0, node="n0") == 5
        off = REGISTRY.gauge("filodb_ingest_watermark_offset")
        assert off.value(dataset="wm1", shard=0, node="n0",
                         stage="broker_end") == 25

    def test_stall_fires_once_per_episode_and_rearms(self):
        ms = TimeSeriesMemStore()
        ms.setup("wm2", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("wm2", 0)
        _ingest_rows(sh, 5)
        head = [20]
        wm = WatermarkLedger(stall_window_s=0.05, node="n1")
        wm.watch("wm2", ms, end_offset_fn=lambda s: head[0])
        stalls = REGISTRY.counter("filodb_ingest_stalls_total")
        before = stalls.value(dataset="wm2", shard=0, node="n1")
        assert wm.sample()["datasets"]["wm2"]["shards"][0]["stalled"] \
            is False
        time.sleep(0.06)
        assert wm.sample()["datasets"]["wm2"]["shards"][0]["stalled"] \
            is True
        wm.sample()  # still stalled; must not double-count
        assert stalls.value(dataset="wm2", shard=0, node="n1") \
            == before + 1
        from filodb_tpu.utils.devicewatch import FLIGHT
        evs = [e for e in FLIGHT.events(kind="ingest.stall")
               if e.get("dataset") == "wm2"]
        assert evs and evs[-1]["lag_rows"] > 0
        # progress re-arms: ingest more, then stall again -> 2nd episode
        _ingest_rows(sh, 5, start_off=5)
        assert wm.sample()["datasets"]["wm2"]["shards"][0]["stalled"] \
            is False
        time.sleep(0.06)
        assert wm.sample()["datasets"]["wm2"]["shards"][0]["stalled"] \
            is True
        assert stalls.value(dataset="wm2", shard=0, node="n1") \
            == before + 2

    def test_close_removes_exported_gauge_rows(self):
        """ISSUE 9 regression: a dead server's ledger rows — above all
        a lingering ``filodb_ingest_stalled=1`` — must leave the
        process registry on close, or the self-monitoring alert rules
        scraping it fire on a node that no longer exists."""
        ms = TimeSeriesMemStore()
        ms.setup("wmclose", DEFAULT_SCHEMAS, 0)
        _ingest_rows(ms.get_shard("wmclose", 0), 5)
        wm = WatermarkLedger(stall_window_s=0.01, node="nx")
        wm.watch("wmclose", ms, end_offset_fn=lambda s: 20)
        wm.sample()
        time.sleep(0.02)
        wm.sample()
        stalled = REGISTRY.gauge("filodb_ingest_stalled")
        assert stalled.value(dataset="wmclose", shard=0, node="nx") == 1

        def gauge_rows(dataset):
            # the LEDGER's gauge family only (the memstore's own
            # cardinality gauges have their own close path)
            return [ln for ln in REGISTRY.expose_text().splitlines()
                    if f'dataset="{dataset}"' in ln
                    and ln.startswith("filodb_ingest_")
                    and not ln.startswith(
                        "filodb_ingest_stalls_total")]

        assert gauge_rows("wmclose")
        wm.close()
        # every GAUGE row is gone (the cumulative stalls_total counter
        # stays — counters are history, gauges are state)
        assert gauge_rows("wmclose") == []
        # unwatch alone drops that dataset's rows too
        ms2 = TimeSeriesMemStore()
        ms2.setup("wmun", DEFAULT_SCHEMAS, 0)
        _ingest_rows(ms2.get_shard("wmun", 0), 5)
        wm2 = WatermarkLedger(node="ny")
        wm2.watch("wmun", ms2, end_offset_fn=lambda s: 20)
        wm2.sample()
        assert gauge_rows("wmun")
        wm2.unwatch("wmun")
        assert gauge_rows("wmun") == []

    def test_caught_up_shard_never_stalls(self):
        ms = TimeSeriesMemStore()
        ms.setup("wm3", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("wm3", 0)
        _ingest_rows(sh, 5)
        wm = WatermarkLedger(stall_window_s=0.01, node="n2")
        wm.watch("wm3", ms, end_offset_fn=lambda s: 5)  # head == ingested+1
        time.sleep(0.03)
        row = wm.sample()["datasets"]["wm3"]["shards"][0]
        assert row["lag"]["rows"] == 0 and row["stalled"] is False

    def test_admin_shards_endpoint_and_flush_snapshot(self):
        from filodb_tpu.memstore.flush import FlushScheduler
        ms = TimeSeriesMemStore()
        ms.setup("wm4", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("wm4", 0)
        _ingest_rows(sh, 10)
        sched = FlushScheduler(sh, flush_interval_ms=60_000)
        sh.flush_scheduler = sched
        srv = FiloHttpServer(node_name="wm4-node")
        srv.bind_dataset(DatasetBinding("wm4", ms, planner=None))
        port = srv.start()
        try:
            code, body = _get(port, "/admin/shards")
            assert code == 200
            ds = body["data"]["datasets"]["wm4"]
            row = ds["shards"][0]
            assert row["watermarks"]["ingested"] == 9
            assert "flush" in row
            assert row["flush"]["pending"] == 0
            assert body["data"]["node"] == "wm4-node"
            assert ds["totals"]["queryable"] == 1
            # runtime stall-window knob via /admin/config
            code, body = _get(port, "/admin/config",
                              **{"ingest-stall-window-s": "7.5"})
            assert code == 200
            assert body["data"]["dataplane"]["ingest-stall-window-s"] == 7.5
            assert srv.watermarks.stall_window_s == 7.5
        finally:
            srv.shutdown()
            sched.close(flush_remaining=False)


# ---------------------------------------------------------------------------
# self-scrape
# ---------------------------------------------------------------------------


class TestSelfScrape:
    def test_parse_exposition_grammar(self):
        from filodb_tpu.gateway.selfscrape import parse_exposition
        text = (
            "# TYPE x counter\n"
            "x_total 41\n"
            'x_labeled{a="1",b="two"} 2.5\n'
            'x_esc{v="a\\"b\\\\c\\nd"} 1\n'
            'hist_bucket{le="+Inf"} 7\n'
            "weird_inf +Inf\n"
            "weird_nan NaN\n")
        got = {name: (labels, v)
               for name, labels, v in parse_exposition(text)}
        assert got["x_total"] == ({}, 41.0)
        assert got["x_labeled"][0] == {"a": "1", "b": "two"}
        assert got["x_esc"][0] == {"v": 'a"b\\c\nd'}
        assert got["hist_bucket"][0] == {"le": "+Inf"}
        assert got["weird_inf"][1] == float("inf")
        assert got["weird_nan"][1] != got["weird_nan"][1]  # NaN

    def test_scrape_publishes_through_gateway_path(self):
        from filodb_tpu.gateway.selfscrape import SelfScraper
        from filodb_tpu.gateway.server import ShardingPublisher
        g = REGISTRY.gauge("selfscrape_probe_gauge")
        g.set(42.5, role="probe")
        published: list = []
        mapper = ShardMapper(1)
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], mapper,
                                lambda s, c: published.append(c), spread=0)
        sc = SelfScraper(pub, interval_s=60,
                         default_tags={"_ws_": "filodb", "_ns_": "node-x",
                                       "instance": "node-x"})
        n = sc.scrape_once()
        assert n > 10 and published
        # decode the containers back: the probe gauge must be present
        # with its exact value and merged tags
        found = []
        for c in published:
            for rec in decode_container(c, DEFAULT_SCHEMAS):
                if rec.tags.get("_metric_") == "selfscrape_probe_gauge":
                    found.append(rec)
        assert found
        rec = found[0]
        assert rec.values[0] == 42.5
        assert rec.tags["role"] == "probe"
        assert rec.tags["_ws_"] == "filodb"
        assert rec.tags["instance"] == "node-x"
        scrapes = REGISTRY.counter("filodb_selfscrape_scrapes_total")
        assert scrapes.value() >= 1

    def test_nonfinite_samples_skipped(self):
        from filodb_tpu.gateway.selfscrape import SelfScraper
        seen: list = []

        class Pub:
            def add_sample(self, metric, tags, ts, value):
                seen.append((metric, value))

            def flush(self):
                return 0

        sc = SelfScraper(Pub(), expose_fn=lambda: "a_inf +Inf\nb_ok 1\n")
        assert sc.scrape_once() == 1
        assert seen == [("b_ok", 1.0)]


# ---------------------------------------------------------------------------
# gateway memo eviction (satellite: no re-parse stampede on label flood)
# ---------------------------------------------------------------------------


class TestHeadMemoEviction:
    def test_evict_memo_half_keeps_newest(self):
        from filodb_tpu.gateway.influx import evict_memo_half
        memo = {f"k{i}": i for i in range(100)}
        evict_memo_half(memo)
        assert len(memo) == 50
        assert "k0" not in memo and "k99" in memo and "k50" in memo

    def test_label_flood_keeps_memo_bounded(self, monkeypatch):
        from filodb_tpu.gateway import influx
        monkeypatch.setattr(influx, "HEAD_MEMO_MAX", 16)
        memo: dict = {}
        # steady series first, then a flood of unique label values
        steady = "app_up,host=h0 value=1 1700000000000000000"
        recs = influx.parse_lines_fast(steady + "\n", memo)
        assert recs[0].tags == {"host": "h0"}
        flood = "\n".join(
            f"app_up,host=flood{i} value=1 1700000000000000000"
            for i in range(100))
        recs = influx.parse_lines_fast(flood + "\n", memo)
        assert len(recs) == 100
        # memo stayed bounded (never wiped to zero, never unbounded)
        assert 0 < len(memo) <= 16
        # the newest flood entries survived the evictions
        assert any(k.startswith("app_up,host=flood9") for k in memo)
        # and parses remain CORRECT after eviction churn
        recs = influx.parse_lines_fast(steady + "\n", memo)
        assert recs[0].tags == {"host": "h0"}
        assert recs[0].fields == {"value": 1.0}

    def test_gateway_series_memo_flood_bounded(self, monkeypatch):
        from filodb_tpu.gateway import influx
        from filodb_tpu.gateway.server import ShardingPublisher
        monkeypatch.setattr(influx, "HEAD_MEMO_MAX", 32)
        mapper = ShardMapper(2)
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], mapper,
                                lambda s, c: None, spread=0)
        total = 0
        for burst in range(4):
            lines = "\n".join(
                f"flood_m,host=b{burst}x{i} value=1.0 "
                f"1700000000000000000" for i in range(50))
            total += pub.ingest_influx_batch(lines + "\n")
        assert total == 200
        assert 0 < len(pub._series_memo) <= 32
        assert pub.parse_errors == 0


# ---------------------------------------------------------------------------
# shard-health emission (satellite: ShardMapper status transitions)
# ---------------------------------------------------------------------------


class TestShardMapperHealth:
    def test_lifecycle_queryable_semantics(self):
        m = ShardMapper(4, dataset="health1")
        assert m.status(0) is ShardStatus.UNASSIGNED
        assert not m.status(0).queryable
        m.register_node([0], "node-a")
        assert m.status(0) is ShardStatus.ASSIGNED
        assert not m.status(0).queryable
        m.update_status(0, ShardStatus.RECOVERY, progress=40)
        assert m.status(0).queryable          # recovery serves reads
        assert m.state(0).recovery_progress == 40
        m.update_status(0, ShardStatus.ACTIVE)
        assert m.status(0).queryable
        assert m.state(0).recovery_progress == 0
        m.update_status(0, ShardStatus.DOWN)
        assert not m.status(0).queryable
        assert m.active_shards() == []

    def test_unassign_resets_progress(self):
        m = ShardMapper(2, dataset="health2")
        m.register_node([1], "n")
        m.update_status(1, ShardStatus.RECOVERY, progress=70)
        m.unassign(1)
        st = m.state(1)
        assert st.status is ShardStatus.UNASSIGNED
        assert st.recovery_progress == 0
        assert st.node is None

    def test_update_status_emits_metric_and_event(self):
        from filodb_tpu.utils.devicewatch import FLIGHT
        m = ShardMapper(2, dataset="health3")
        m.register_node([0], "n")
        code = REGISTRY.gauge("filodb_shard_status_code")
        prog = REGISTRY.gauge("filodb_shard_recovery_progress")
        trans = REGISTRY.counter("filodb_shard_status_transitions_total")
        before = trans.value(dataset="health3", status="Recovery")
        m.update_status(0, ShardStatus.RECOVERY, progress=55)
        assert code.value(dataset="health3", shard=0) == 2
        assert prog.value(dataset="health3", shard=0) == 55
        assert trans.value(dataset="health3",
                           status="Recovery") == before + 1
        evs = [e for e in FLIGHT.events(kind="shard.status")
               if e.get("dataset") == "health3"]
        assert evs and evs[-1]["status"] == "Recovery" \
            and evs[-1]["prev"] == "Assigned"
        # re-applying the same status (status-poller sweeps) is silent
        n_evs = len(FLIGHT.events(kind="shard.status"))
        m.update_status(0, ShardStatus.RECOVERY, progress=55)
        assert len(FLIGHT.events(kind="shard.status")) == n_evs
        assert trans.value(dataset="health3",
                           status="Recovery") == before + 1
        # progress-only change refreshes the gauge without a transition
        m.update_status(0, ShardStatus.RECOVERY, progress=80)
        assert prog.value(dataset="health3", shard=0) == 80
        assert trans.value(dataset="health3",
                           status="Recovery") == before + 1

    def test_anonymous_mapper_stays_silent(self):
        from filodb_tpu.utils.devicewatch import FLIGHT
        n_evs = len(FLIGHT.events(kind="shard.status"))
        m = ShardMapper(2)  # no dataset: benches/ad-hoc tests
        m.register_node([0], "n")
        m.update_status(0, ShardStatus.ACTIVE)
        assert len(FLIGHT.events(kind="shard.status")) == n_evs


# ---------------------------------------------------------------------------
# CLI verbs
# ---------------------------------------------------------------------------


class TestCliVerbs:
    def test_cardinality_report_and_shards(self, capsys):
        from filodb_tpu.cli import main as cli_main
        ms = TimeSeriesMemStore()
        ms.setup("cliq", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("cliq", 0)
        for i in range(6):
            tags = {"__name__": "m", "u": str(i), "_ws_": "w",
                    "_ns_": f"t{i % 2}"}
            for c in _one_row_container(tags, BASE + i):
                sh.ingest_container(c, i)
        srv = FiloHttpServer()
        srv.bind_dataset(DatasetBinding("cliq", ms, planner=None))
        port = srv.start()
        try:
            assert cli_main(["cardinality-report", "--server",
                             f"http://127.0.0.1:{port}",
                             "--dataset", "cliq", "--topk", "3"]) == 0
            out = capsys.readouterr().out
            assert "6 active series" in out
            assert "tenant t0" in out and "tenant t1" in out
            assert cli_main(["cardinality-report", "--server",
                             f"http://127.0.0.1:{port}",
                             "--dataset", "cliq", "--json"]) == 0
            data = json.loads(capsys.readouterr().out)
            assert data["total_active_series"] == 6
            assert cli_main(["shards", "--server",
                             f"http://127.0.0.1:{port}",
                             "--dataset", "cliq"]) == 0
            body = json.loads(capsys.readouterr().out)
            shards = body["data"]["datasets"]["cliq"]["shards"]
            assert shards[0]["watermarks"]["ingested"] == 5
            # unknown dataset surfaces the server's error, exit 1
            assert cli_main(["cardinality-report", "--server",
                             f"http://127.0.0.1:{port}",
                             "--dataset", "nope"]) == 1
            capsys.readouterr()
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# review-fix regressions
# ---------------------------------------------------------------------------


class TestReviewFixes:
    def test_steady_head_survives_interleaved_flood(self, monkeypatch):
        """Memo hits refresh recency, so a steady series touched every
        batch stays cached across flood-driven evictions (insertion
        order would evict the fleet first — the stampede)."""
        from filodb_tpu.gateway import influx
        monkeypatch.setattr(influx, "HEAD_MEMO_MAX", 16)
        memo: dict = {}
        steady = "fleet_up,host=h0 value=1 1700000000000000000"
        influx.parse_lines_fast(steady + "\n", memo)
        for burst in range(10):   # each burst overflows at least once
            flood = "\n".join(
                f"fleet_up,host=fl{burst}x{i} value=1 1700000000000000000"
                for i in range(12))
            influx.parse_lines_fast(flood + "\n", memo)
            # steady traffic between floods: the hit must re-rank it
            influx.parse_lines_fast(steady + "\n", memo)
            assert "fleet_up,host=h0" in memo, f"evicted at burst {burst}"
        assert len(memo) <= 16

    def test_tenant_gauge_row_removed_when_tenant_drains(self):
        from filodb_tpu.memstore.cardinality import sample_tenant_gauges
        ms = TimeSeriesMemStore()
        ms.setup("drain", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("drain", 0)
        off = 0
        for tenant, n in (("keep", 3), ("gone", 2)):
            for i in range(n):
                tags = {"__name__": "m", "u": f"{tenant}{i}",
                        "_ws_": "w", "_ns_": tenant}
                for c in _one_row_container(tags, BASE + i):
                    sh.ingest_container(c, off)
                    off += 1
        sample_tenant_gauges("drain", ms.shards("drain"))
        gauge = REGISTRY.gauge("filodb_index_cardinality_tenant_series")
        assert gauge.value(dataset="drain", tenant="gone") == 2
        # drain tenant "gone": stop + evict its series
        for pid in list(sh.partitions):
            if sh.index.tags(pid)["_ns_"] == "gone":
                sh.index.update_end_time(pid, BASE)
        sh.evict_partitions(2)
        merged = sample_tenant_gauges("drain", ms.shards("drain"))
        assert merged == {"keep": 3}
        assert gauge.value(dataset="drain", tenant="gone") == 0.0
        rows = [ln for ln in gauge.expose() if 'dataset="drain"' in ln]
        assert not any('tenant="gone"' in ln for ln in rows), rows

    def test_shard_filtered_report_does_not_clobber_gauges(self):
        from filodb_tpu.memstore.cardinality import build_report
        ms = TimeSeriesMemStore()
        for s in (0, 1):
            ms.setup("fleet", DEFAULT_SCHEMAS, s)
        off = 0
        for s in (0, 1):
            sh = ms.get_shard("fleet", s)
            for i in range(4):
                tags = {"__name__": "m", "u": f"s{s}_{i}",
                        "_ws_": "w", "_ns_": "tX"}
                for c in _one_row_container(tags, BASE + i):
                    sh.ingest_container(c, off)
                    off += 1
        build_report("fleet", ms.shards("fleet"))   # full: sets gauges
        gauge = REGISTRY.gauge("filodb_index_cardinality_tenant_series")
        assert gauge.value(dataset="fleet", tenant="tX") == 8
        rep = build_report("fleet", ms.shards("fleet"), shard_num=0)
        assert rep["tenants"] == {"tX": 4}          # filtered view...
        assert gauge.value(dataset="fleet", tenant="tX") == 8  # ...gauge not

    def test_concurrent_samples_fire_one_stall(self):
        """Sampler thread + inline /admin/shards requests racing across
        the stall boundary must still count ONE episode."""
        ms = TimeSeriesMemStore()
        ms.setup("race", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("race", 0)
        _ingest_rows(sh, 3)
        wm = WatermarkLedger(stall_window_s=0.05, node="rc")
        wm.watch("race", ms, end_offset_fn=lambda s: 50)
        stalls = REGISTRY.counter("filodb_ingest_stalls_total")
        before = stalls.value(dataset="race", shard=0, node="rc")
        wm.sample()                 # arm the episode
        time.sleep(0.07)
        barrier = threading.Barrier(8)

        def racer():
            barrier.wait()
            for _ in range(5):
                wm.sample()

        threads = [threading.Thread(target=racer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert stalls.value(dataset="race", shard=0, node="rc") \
            == before + 1
