"""Rule-engine e2e (ISSUE 9 acceptance criteria).

1. A chaos-injected ingest stall (NodeChaosController.stall_ingest)
   drives the shipped self-monitoring pack through the full alert
   lifecycle — inactive -> pending -> firing -> resolved — with
   correct ``ALERTS`` synthetic series written into the ``_system``
   dataset and exactly one webhook delivery per notifying transition.

2. A recording rule's written-back series rides the PR 12 dual-write
   fanout: queryable via PromQL on the REPLICA node with values
   bit-equal to evaluating the source expr directly.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from filodb_tpu.integrity.faultinject import NodeChaosController
from filodb_tpu.parallel.shardmap import ShardStatus
from filodb_tpu.standalone import FiloServer

BASE = 1_700_000_000_000


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path, timeout=20, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class _WebhookSink:
    """In-process webhook receiver recording every delivered payload."""

    def __init__(self):
        self.deliveries: list[dict] = []
        self._lock = threading.Lock()
        sink = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                ln = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(ln))
                with sink._lock:
                    sink.deliveries.extend(body)
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="webhook-sink",
            daemon=True)
        self._thread.start()

    def of(self, alertname: str, status: str, **labels) -> list:
        with self._lock:
            return [d for d in self.deliveries
                    if d.get("labels", {}).get("alertname") == alertname
                    and d.get("status") == status
                    and all(d.get("labels", {}).get(k) == v
                            for k, v in labels.items())]

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _wait(predicate, timeout_s, what, interval=0.05):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {what}")


class TestSelfMonitoringStallAlert:
    def test_chaos_stall_drives_full_alert_lifecycle(self):
        sink = _WebhookSink()
        config = {
            "node": "rules-node",
            "datasets": [{"name": "prom", "num-shards": 1,
                          "min-num-nodes": 1, "schema": "gauge",
                          "spread": 0}],
            "dataplane": {
                "watermark-sample-interval-s": 0.15,
                "ingest-stall-window-s": 0.4,
                "self-scrape": {"enabled": True, "interval-s": 0.15,
                                "dataset": "_system"},
            },
            "rules": {
                "notifier": {"url":
                             f"http://127.0.0.1:{sink.port}/alerts",
                             "retries": 2, "backoff-s": 0.05},
                "self-monitoring": {"interval": "400ms", "for": "900ms",
                                    "window": "6s"},
            },
        }
        srv = FiloServer(config)
        port = srv.start()
        chaos = NodeChaosController()
        ic = srv.coordinator.ingestion["prom"]
        chaos.register(
            "rules-node",
            stall_ingest_fn=lambda: ic.stop_ingestion(0),
            resume_ingest_fn=lambda: ic.start_ingestion(0))
        alert = "FiloIngestStalled"
        try:
            # the standalone server loaded the shipped pack
            code, body = _get(port, "/api/v1/rules")
            assert code == 200
            (group,) = body["data"]["groups"]
            assert group["name"] == "filodb-self-monitoring"
            names = {r["name"] for r in group["rules"]}
            assert {"FiloIngestStalled", "FiloRecompileStorm",
                    "FiloReplicaPublishFailing", "FiloChunksQuarantined",
                    "node:ingest_lag_rows:sum"} <= names
            # self-scrape flowing into _system
            _wait(lambda: sum(sh.stats.rows_ingested
                              for sh in srv.memstore.shards("_system"))
                  > 100, 20, "self-scrape rows")
            # the pack's RECORDING rules write back: a recorded series
            # is PromQL-queryable in _system through the normal path
            def recorded_visible():
                now_s = time.time()
                _code, b = _get(
                    port, "/promql/_system/api/v1/query_range",
                    query='node:ingest_lag_rows:sum{source="selfmon"}',
                    start=now_s - 30, end=now_s, step="1s")
                return b.get("data", {}).get("result")
            _wait(recorded_visible, 20, "recorded write-back series")

            # ---- chaos: wedge prom's ingest consumer, keep producing
            pub = srv.write_publishers["prom"]
            chaos.stall_ingest("rules-node")
            assert ("stall_ingest", "rules-node") in chaos.events
            stop_feed = threading.Event()

            def feeder():
                i = 0
                while not stop_feed.is_set():
                    pub.add_sample("stall_m",
                                   {"inst": "a", "_ws_": "w",
                                    "_ns_": "n"},
                                   int(time.time() * 1000), float(i))
                    pub.flush()
                    i += 1
                    time.sleep(0.05)

            # alerts for THIS server's dataset only: earlier tests in a
            # full-suite run may have left other datasets' gauge rows
            # in the process-global registry (bare ledgers never call
            # close()), and the self-scrape faithfully reports them
            def stall_alerts(state=None):
                return [a for a in _get(
                    port, "/api/v1/alerts")[1]["data"]["alerts"]
                    if a["labels"]["alertname"] == alert
                    and a["labels"].get("dataset") == "prom"
                    and (state is None or a["state"] == state)]

            feed = threading.Thread(target=feeder, daemon=True)
            feed.start()
            try:
                # lifecycle: pending ...
                _wait(stall_alerts, 30, "stall alert active")
                # ... then firing (past the `for:` hold)
                _wait(lambda: stall_alerts("firing"), 30,
                      "stall alert firing")
            finally:
                stop_feed.set()
                feed.join(timeout=5)
            # ---- heal: consumer resumes, backlog drains, the stall
            # level gauge clears -> resolved
            chaos.resume_ingest("rules-node")
            _wait(lambda: not stall_alerts(), 40,
                  "stall alert resolved")

            # exactly one notifier delivery per notifying transition
            _wait(lambda: sink.of(alert, "resolved", dataset="prom"),
                  20, "resolved webhook delivery")
            assert len(sink.of(alert, "firing", dataset="prom")) == 1
            assert len(sink.of(alert, "resolved", dataset="prom")) == 1
            fired = sink.of(alert, "firing", dataset="prom")[0]
            assert fired["labels"]["severity"] == "page"
            assert fired["labels"]["dataset"] == "prom"
            assert "ingest stalled" in fired["annotations"]["summary"]

            # ALERTS synthetic series landed in _system with the right
            # alertstate progression, queryable through PromQL
            now_s = time.time()
            code, body = _get(
                port, "/promql/_system/api/v1/query_range",
                query=f'ALERTS{{alertname="{alert}",dataset="prom"}}',
                start=now_s - 60, end=now_s, step="1s")
            assert code == 200
            states = set()
            for series in body["data"]["result"]:
                states.add(series["metric"]["alertstate"])
                assert all(float(v) == 1.0
                           for _t, v in series["values"])
            assert states == {"pending", "firing"}
            code, body = _get(
                port, "/promql/_system/api/v1/query_range",
                query=f'ALERTS_FOR_STATE{{alertname="{alert}",'
                      f'dataset="prom"}}',
                start=now_s - 60, end=now_s, step="1s")
            assert body["data"]["result"], "ALERTS_FOR_STATE missing"

            # the engine's own telemetry: transitions counted, live
            # state endpoint reflects the pass history
            from filodb_tpu.utils.observability import REGISTRY
            tr = REGISTRY.counter("filodb_rule_alert_transitions_total")
            g = "filodb-self-monitoring"
            assert tr.value(group=g, state="pending") >= 1
            assert tr.value(group=g, state="firing") >= 1
            assert tr.value(group=g, state="resolved") >= 1
            code, body = _get(port, "/admin/rules")
            assert code == 200
            row = body["data"]["groups"][0]
            assert row["evals"] > 2
            assert body["data"]["notifier"]["queue_depth"] == 0
            # flight events on firing/resolve (the black box)
            from filodb_tpu.utils.devicewatch import FLIGHT
            evs = [e for e in FLIGHT.events(kind="rules.alert")
                   if e.get("alertname") == alert]
            assert {"pending", "firing", "resolved"} <= \
                {e["state"] for e in evs}
        finally:
            srv.shutdown()
            sink.close()


class TestRecordedSeriesOnReplica:
    def test_write_back_replicated_and_bit_equal(self):
        """Recording-rule output rides the rf=2 dual-write fanout: the
        REPLICA node serves the recorded series via PromQL with values
        bit-equal to evaluating the source expr directly."""
        ports = {"rr-a": _free_port(), "rr-b": _free_port()}
        peers = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
        servers = {}
        expr = "rate(rep_total[60s])"
        try:
            for n in ("rr-a", "rr-b"):
                cfg = {
                    "node": n, "http-port": ports[n], "peers": peers,
                    "status-poll-interval-s": 0.2,
                    "dataplane": {"watermark-sample-interval-s": 3600},
                    "datasets": [{"name": "rep", "num-shards": 2,
                                  "min-num-nodes": 2,
                                  "replication-factor": 2,
                                  "schema": "gauge", "spread": 1}],
                }
                if n == "rr-a":
                    # interval 1h: the periodic loop stays out of the
                    # way; the test drives deterministic evals itself
                    cfg["rules"] = {"groups": [{
                        "name": "rg", "interval": "1h", "dataset": "rep",
                        "rules": [{"record": "job:rep:rate",
                                   "expr": expr}]}]}
                servers[n] = FiloServer(cfg)
                servers[n].start()
            m = servers["rr-a"].manager.mapper("rep")
            _wait(lambda: all(
                len(m.live_replicas(s)) == 2
                and all(r.status is ShardStatus.ACTIVE
                        for r in m.live_replicas(s))
                for s in range(2)), 30, "rf=2 assignment")

            pub = servers["rr-a"].write_publishers["rep"]
            rng = np.random.default_rng(3)
            vals = {f"i{i}": np.cumsum(rng.random(90)) * 7
                    for i in range(6)}
            for inst, vv in vals.items():
                for k in range(90):
                    pub.add_sample("rep_total",
                                   {"inst": inst, "_ws_": "w",
                                    "_ns_": "n"},
                                   BASE + k * 1000, float(vv[k]))
            pub.flush()
            need = 6 * 90
            _wait(lambda: all(
                sum(sh.stats.rows_ingested
                    for sh in servers[n].memstore.shards("rep")) >= need
                for n in ("rr-a", "rr-b")), 30, "dual-write ingest")

            eval_ms = BASE + 89_000
            eng = servers["rr-a"].rule_engine
            assert eng is not None
            eng.run_group_once("rg", eval_ms=eval_ms)
            # the recorded samples dual-write like any ingest: wait for
            # the replica to hold them, then query the REPLICA
            def replica_serves():
                _c, b = _get(ports["rr-b"],
                             "/promql/rep/api/v1/query",
                             query="job:rep:rate",
                             time=eval_ms / 1000.0)
                got = b.get("data", {}).get("result") or []
                # per-shard peer lanes land asynchronously: wait for
                # EVERY recorded series, not the first shard's batch
                return got if len(got) == len(vals) else None
            result = _wait(replica_serves, 30,
                           "all recorded series on the replica")
            recorded = {r["metric"]["inst"]: float(r["value"][1])
                        for r in result}
            assert set(recorded) == set(vals)
            # direct evaluation of the source expr at the same instant,
            # on the same replica
            _c, b = _get(ports["rr-b"], "/promql/rep/api/v1/query",
                         query=expr, time=eval_ms / 1000.0)
            direct = {r["metric"]["inst"]: float(r["value"][1])
                      for r in b["data"]["result"]}
            assert set(direct) == set(recorded)
            for inst, v in recorded.items():
                assert np.float64(v).tobytes() == \
                    np.float64(direct[inst]).tobytes(), inst
        finally:
            for srv in servers.values():
                srv.shutdown()
