"""Mesh query fabric (ISSUE 18): the fused single-launch path must be
BIT-equal to the scatter-gather oracle, and every fallback rung
(breaker trip, stale topology, mixed residency, live 4->8 split) must
answer byte-for-byte the same.

Every scalar dataset here is DYADIC-exact — integers scaled by 2^-3 —
so every f64 sum is exact at ANY summation order and the fused
cross-shard psum, the partial-mesh host reduce, and the per-shard
oracle all produce identical bits (histograms use integer cumulative
bucket counts for the same reason).  Comparisons are tobytes + an
explicit NaN-mask check, not allclose.

Runs on the 8-device virtual CPU mesh from tests/conftest.py.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel import meshexec, meshgrid
from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
from filodb_tpu.parallel.shardmap import ShardMapper, shard_of_tags
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext, IN_PROCESS
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.devicewatch import KERNEL_TIMER, device_metrics

BASE = 1_700_000_000_000
STEP = 10_000
N_ROWS = 90
START, END = BASE + 300_000, BASE + 800_000


def _dyadic_series(rng, n_rows):
    """Multiples of 1/8 below 2^37: sums of thousands of these stay
    exact integers*2^-3 < 2^53, so f64 addition is order-independent."""
    return rng.integers(1, 1 << 40, n_rows).astype(np.float64) / 8.0


def _mk_store(num_shards, spread, n_series=24, seed=7):
    ms = TimeSeriesMemStore()
    opts = DatasetOptions()
    mapper = ShardMapper(num_shards)
    for s in range(num_shards):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(seed)
    for i in range(n_series):
        tags = {"_metric_": "fm", "inst": f"i{i}", "grp": f"g{i % 3}",
                "_ws_": "w", "_ns_": "n"}
        shard = shard_of_tags(tags, num_shards, spread, opts)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = BASE + np.arange(N_ROWS) * STEP
        b.add_series(ts.tolist(), [_dyadic_series(rng, N_ROWS).tolist()],
                     tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", shard).ingest_container(c, off)
    return ms, mapper


def _planner(mapper, spread, mesh=False, dispatcher_for_shard=None,
             mesh_fused=True):
    provider = None
    if mesh:
        engine = MeshEngine(make_mesh())
        provider = lambda: engine  # noqa: E731
    return SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                spread_default=spread,
                                dispatcher_for_shard=dispatcher_for_shard,
                                mesh_engine_provider=provider,
                                mesh_fused=mesh_fused)


def _run(planner, ms, promql, start=START, end=END, step=30_000):
    plan = query_range_to_logical_plan(promql, start, step, end)
    ep = planner.materialize(plan, QueryContext())
    result = ep.execute(ExecContext(ms, QueryContext()))
    out = {}
    for b in result.batches:
        for tags, ts, vals in b.to_series():
            out[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                np.asarray(vals))
    return out


def _assert_biteq(fused, plain, msg=""):
    """tobytes equality + the NaN pattern compared explicitly."""
    assert set(fused) == set(plain) and plain, msg
    for k in plain:
        np.testing.assert_array_equal(fused[k][0], plain[k][0],
                                      err_msg=f"{msg} {k} (timestamps)")
        a = np.asarray(fused[k][1], dtype=np.float64)
        b = np.asarray(plain[k][1], dtype=np.float64)
        assert np.array_equal(np.isnan(a), np.isnan(b)), \
            f"{msg} {k}: NaN pattern differs"
        assert a.tobytes() == b.tobytes(), \
            f"{msg} {k}: answers not bit-equal"


SWEEP_QUERIES = [
    'sum by (grp)(fm{_ws_="w",_ns_="n"})',
    'sum(fm{_ws_="w",_ns_="n"})',
    'count(fm{_ws_="w",_ns_="n"})',
    'avg by (grp)(fm{_ws_="w",_ns_="n"})',
    'min(fm{_ws_="w",_ns_="n"})',
    'max by (grp)(fm{_ws_="w",_ns_="n"})',
    'group by (grp)(fm{_ws_="w",_ns_="n"})',
    'topk(2, fm{_ws_="w",_ns_="n"})',
]

# (num_shards, spread, seed): randomized shard counts for the sweep
SHAPES = [(2, 1, 17), (4, 2, 23), (8, 3, 31)]


@pytest.fixture(scope="module", params=SHAPES,
                ids=[f"{n}shards" for n, _, _ in SHAPES])
def sweep_store(request):
    n, spread, seed = request.param
    ms, mapper = _mk_store(n, spread, seed=seed)
    return ms, mapper, spread


@pytest.fixture(scope="module")
def hist_store():
    from tests.data import histogram_containers
    ms = TimeSeriesMemStore()
    mapper = ShardMapper(4)
    for s in range(4):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    for shard_num in (0, 1, 2):
        for off, c in enumerate(histogram_containers(
                n_series=2, n_samples=40, metric="hq", seed=shard_num)):
            ms.get_shard("prom", shard_num).ingest_container(c, off)
    return ms, mapper


class TestFusedBitEquality:
    @pytest.mark.parametrize("promql", SWEEP_QUERIES)
    def test_sweep_matches_oracle_bitwise(self, sweep_store, promql):
        ms, mapper, spread = sweep_store
        plain = _run(_planner(mapper, spread), ms, promql)
        fused = _run(_planner(mapper, spread, mesh=True), ms, promql)
        _assert_biteq(fused, plain, promql)

    def test_plan_root_is_fused_node(self, sweep_store):
        ms, mapper, spread = sweep_store
        planner = _planner(mapper, spread, mesh=True)
        plan = query_range_to_logical_plan(
            'sum by (grp)(fm{_ws_="w",_ns_="n"})', START, 30_000, END)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" in tree
        assert "ReduceAggregateExec" not in tree

    def test_mesh_fused_knob_pins_partial_shape(self, sweep_store):
        ms, mapper, spread = sweep_store
        planner = _planner(mapper, spread, mesh=True, mesh_fused=False)
        promql = 'sum by (grp)(fm{_ws_="w",_ns_="n"})'
        plan = query_range_to_logical_plan(promql, START, 30_000, END)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" not in tree
        assert "MeshAggregateExec" in tree
        _assert_biteq(_run(planner, ms, promql),
                      _run(_planner(mapper, spread), ms, promql), promql)


class TestHistogramQuantileFusion:
    PHI_Q = 'histogram_quantile(0.9, sum(hq{_ws_="demo",_ns_="App-0"}))'
    SUM_Q = 'sum(hq{_ws_="demo",_ns_="App-0"})'
    # first step at +300_000 so the 5m lookback window stays inside the
    # ingested span — a window reaching before epoch0 demotes the grid
    HSTART = 1_600_000_000_000 + 300_000
    HEND = 1_600_000_000_000 + 390_000

    def test_phi_folds_into_fused_root(self, hist_store):
        ms, mapper = hist_store
        planner = _planner(mapper, 2, mesh=True)
        plan = query_range_to_logical_plan(self.PHI_Q, self.HSTART,
                                           30_000, self.HEND)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" in tree and "phi=0.9" in tree

    @pytest.mark.parametrize("promql", [SUM_Q, PHI_Q])
    def test_hist_bitequal(self, hist_store, promql):
        ms, mapper = hist_store
        plain = _run(_planner(mapper, 2), ms, promql,
                     start=self.HSTART, end=self.HEND)
        fused = _run(_planner(mapper, 2, mesh=True), ms, promql,
                     start=self.HSTART, end=self.HEND)
        _assert_biteq(fused, plain, promql)


class TestSingleLaunch:
    """Acceptance: a warm mesh-resident N-shard aggregation is ONE
    compiled launch — filodb_kernel_launches_total advances by exactly
    one, on exactly the fused program, at 1-in-1 sampling."""

    def _one_launch(self, ms, mapper, spread, promql, program,
                    start=START, end=END):
        planner = _planner(mapper, spread, mesh=True)
        prev = KERNEL_TIMER.sample_1_in
        KERNEL_TIMER.configure(sample_1_in=1)
        try:
            _run(planner, ms, promql, start=start, end=end)  # warm/compile
            c = device_metrics()["kernel_launches"]
            before_prog = c.value(program=program)
            before_total = c.total()
            _run(planner, ms, promql, start=start, end=end)
            assert c.value(program=program) - before_prog == 1.0
            assert c.total() - before_total == 1.0, \
                "warm fused query launched more than the ONE program"
        finally:
            KERNEL_TIMER.configure(sample_1_in=prev)

    def test_sum_by_is_one_launch(self, sweep_store):
        ms, mapper, spread = sweep_store
        self._one_launch(ms, mapper, spread,
                         'sum by (grp)(fm{_ws_="w",_ns_="n"})',
                         "meshgrid.fused")

    def test_hist_quantile_is_one_launch(self, hist_store):
        ms, mapper = hist_store
        self._one_launch(
            ms, mapper, 2, TestHistogramQuantileFusion.PHI_Q,
            "meshgrid.fused_histq",
            start=TestHistogramQuantileFusion.HSTART,
            end=TestHistogramQuantileFusion.HEND)


class TestFallbackLadder:
    def test_breaker_trip_serves_scatter_gather_bitequal(
            self, sweep_store, monkeypatch):
        ms, mapper, spread = sweep_store
        promql = 'sum by (grp)(fm{_ws_="w",_ns_="n"})'
        plain = _run(_planner(mapper, spread), ms, promql)
        meshexec.reset_fabric_breaker()
        trips0 = meshexec.FABRIC_BREAKER["trips"]

        def boom(*a, **k):
            raise RuntimeError("injected fabric fault")

        monkeypatch.setattr(meshgrid, "serve_grid_mesh_presented", boom)
        try:
            got = _run(_planner(mapper, spread, mesh=True), ms, promql)
            _assert_biteq(got, plain, "breaker-trip answer")
            assert meshexec.FABRIC_BREAKER["open"]
            assert meshexec.FABRIC_BREAKER["trips"] == trips0 + 1
            monkeypatch.undo()
            # breaker still open: later queries keep scatter-gather
            # without touching the fused program
            falls0 = meshgrid.STATS["fallbacks"]
            got = _run(_planner(mapper, spread, mesh=True), ms, promql)
            _assert_biteq(got, plain, "breaker-open answer")
            assert meshgrid.STATS["fallbacks"] > falls0
        finally:
            meshexec.reset_fabric_breaker()
        # closed again: the fused rung serves
        serves0 = meshgrid.STATS["fused_serves"]
        _assert_biteq(_run(_planner(mapper, spread, mesh=True), ms, promql),
                      plain, "post-reset answer")
        assert meshgrid.STATS["fused_serves"] == serves0 + 1

    def test_mixed_residency_degrades_bitequal(self, sweep_store):
        """A shard behind a non-in-process dispatcher keeps the partial
        shape (mesh child + per-shard child + host reduce) — same
        bytes."""
        ms, mapper, spread = sweep_store

        class LoopbackDispatcher:
            def dispatch(self, plan, ctx):
                return plan.execute(ctx)

        lb = LoopbackDispatcher()
        last = mapper.num_shards - 1

        def disp(shard):
            return lb if shard == last else IN_PROCESS

        promql = 'sum by (grp)(fm{_ws_="w",_ns_="n"})'
        plain = _run(_planner(mapper, spread), ms, promql)
        planner = _planner(mapper, spread, mesh=True,
                           dispatcher_for_shard=disp)
        plan = query_range_to_logical_plan(promql, START, 30_000, END)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" not in tree      # not fully resident
        if mapper.num_shards > 2:
            assert "MeshAggregateExec" in tree   # local majority fused
        assert "MultiSchemaPartitionsExec" in tree
        _assert_biteq(_run(planner, ms, promql), plain, "mixed residency")

    def test_feed_shards_fuse_only_when_everything_is_local(self,
                                                            sweep_store):
        """Replicated shards qualify through the dispatcher's
        ``mesh_feed`` hook (this node's copy is the ReplicaSet.pick
        primary) ONLY when that makes every child shard local — the
        fully-fused root.  They must never ride the partial-mesh shape:
        a per-node mix of mesh and dispatched legs would regroup the
        float reduce differently on every replica-holding node
        (test_split_e2e.py's cross-node bit-equality contract)."""
        ms, mapper, spread = sweep_store

        class LoopbackDispatcher:
            def dispatch(self, plan, ctx):
                return plan.execute(ctx)

        lb = LoopbackDispatcher()
        promql = 'sum by (grp)(fm{_ws_="w",_ns_="n"})'
        plain = _run(_planner(mapper, spread), ms, promql)
        plan = query_range_to_logical_plan(promql, START, 30_000, END)

        # every shard replicated (never IN_PROCESS), every copy primary
        # here -> the fused root engages through mesh_feed alone
        def disp_all(shard):
            return lb
        disp_all.mesh_feed = lambda shard: True
        planner = _planner(mapper, spread, mesh=True,
                           dispatcher_for_shard=disp_all)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" in tree, tree
        _assert_biteq(_run(planner, ms, promql), plain, "all-feed fused")

        # one shard NOT primary here -> feed shards must not enlarge the
        # partial shape: no mesh node at all (no shard is IN_PROCESS),
        # plain scatter-gather, same bytes
        last = mapper.num_shards - 1

        def disp_partial(shard):
            return lb
        disp_partial.mesh_feed = lambda shard: shard != last
        planner = _planner(mapper, spread, mesh=True,
                           dispatcher_for_shard=disp_partial)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" not in tree, tree
        assert "MeshAggregateExec" not in tree, tree
        _assert_biteq(_run(planner, ms, promql), plain, "partial feed")

        # mesh-fused off -> feed is ignored outright (the PR 17 shape
        # only ever builds from IN_PROCESS shards)
        planner = _planner(mapper, spread, mesh=True,
                           dispatcher_for_shard=disp_all,
                           mesh_fused=False)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshReduceExec" not in tree, tree
        assert "MeshAggregateExec" not in tree, tree
        _assert_biteq(_run(planner, ms, promql), plain,
                      "feed with fusion off")


class TestEventTopK:
    def test_fused_matches_host_selection_bitequal(self, sweep_store):
        ms, mapper, spread = sweep_store
        plan = query_range_to_logical_plan(
            'sum by (grp)(fm{_ws_="w",_ns_="n"})', START, 30_000, END)
        raw = plan.vectors.raw_series
        engine = MeshEngine(make_mesh())

        def node():
            return meshexec.EventTopKExec(
                "prom", list(range(mapper.num_shards)), raw.filters,
                raw.range_selector.from_ms, raw.range_selector.to_ms,
                START, 30_000, END, k=2, by=("grp",),
                query_context=QueryContext(), engine=engine,
                mapper=mapper,
                planned_generation=mapper.topology_generation)

        def collect(result):
            out = {}
            for b in result.batches:
                for tags, ts, vals in b.to_series():
                    out[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                        np.asarray(vals))
            return out

        fused = collect(node().execute(ExecContext(ms, QueryContext())))
        meshexec.FABRIC_BREAKER["open"] = True
        try:
            host = collect(node().execute(ExecContext(ms, QueryContext())))
        finally:
            meshexec.reset_fabric_breaker()
        _assert_biteq(fused, host, "event-topk fused vs host selection")
        # the winners' rows carry values; losers' rows stay all-NaN
        assert any(np.isfinite(v).any() for _, v in fused.values())


class TestSplitChaos:
    """Satellite: answers stay bit-equal through a live 4->8 split under
    the fabric — fused pre-split, per-shard while the cutover/exclusion
    window is active (including a query PLANNED pre-cutover and executed
    after), and fused again over 8 shards once the split retires."""

    N_SERIES = 48
    SPREAD = 2
    Q = 'sum by (grp)(fm)'      # no shard-key filter: full fan-out

    def _mk_split_store(self):
        ms = TimeSeriesMemStore()
        opts = DatasetOptions()
        mapper = ShardMapper(4)
        for s in range(8):                 # children pre-provisioned
            ms.setup("prom", DEFAULT_SCHEMAS, s)
        rng = np.random.default_rng(41)
        for i in range(self.N_SERIES):
            tags = {"_metric_": "fm", "inst": f"i{i}",
                    "grp": f"g{i % 3}", "_ws_": f"w{i % 5}",
                    "_ns_": f"n{i % 2}"}
            parent = shard_of_tags(tags, 4, self.SPREAD, opts)
            child = shard_of_tags(tags, 8, self.SPREAD, opts)
            b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                              container_size=1 << 20)
            ts = BASE + np.arange(N_ROWS) * STEP
            b.add_series(ts.tolist(),
                         [_dyadic_series(rng, N_ROWS).tolist()], tags)
            targets = {parent, child}      # parent superset + caught-up
            for off, c in enumerate(b.containers()):
                for t in targets:
                    ms.get_shard("prom", t).ingest_container(c, off)
        return ms, mapper

    def test_bitequal_through_4_to_8_split(self):
        ms, mapper = self._mk_split_store()
        oracle = _run(_planner(mapper, self.SPREAD), ms, self.Q)
        fused_planner = _planner(mapper, self.SPREAD, mesh=True)

        # pre-split: fused root, bit-equal
        plan = query_range_to_logical_plan(self.Q, START, 30_000, END)
        ep_pre = fused_planner.materialize(plan, QueryContext())
        assert "MeshReduceExec" in ep_pre.print_tree()
        _assert_biteq(_run(fused_planner, ms, self.Q), oracle, "pre-split")

        # catch-up phase: generation bumped — the PRE-planned program
        # must stand down per-shard (its placement is stale) while a
        # freshly planned query still fuses over the 4 parents
        mapper.begin_split(self.SPREAD)
        falls0 = meshgrid.STATS["fallbacks"]
        got = {}
        res = ep_pre.execute(ExecContext(ms, QueryContext()))
        for b in res.batches:
            for tags, ts, vals in b.to_series():
                got[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                    np.asarray(vals))
        _assert_biteq(got, oracle, "stale-generation fallback")
        assert meshgrid.STATS["fallbacks"] > falls0
        _assert_biteq(_run(fused_planner, ms, self.Q), oracle, "catchup")

        # cutover: reshard exclusions active — planner refuses to fuse,
        # per-shard leaves slice the migrated half, bytes unchanged
        mapper.commit_split()
        plan2 = query_range_to_logical_plan(self.Q, START, 30_000, END)
        tree2 = fused_planner.materialize(plan2,
                                          QueryContext()).print_tree()
        assert "MeshReduceExec" not in tree2
        _assert_biteq(_run(fused_planner, ms, self.Q), oracle, "serving")

        # retire + finish: parents purge their migrated half and the
        # fabric fuses the full 8-shard topology in one program again
        mapper.retire_split()
        for p in range(4):
            ms.get_shard("prom", p).purge_resharded(8, self.SPREAD)
        mapper.finish_split()
        plan3 = query_range_to_logical_plan(self.Q, START, 30_000, END)
        tree3 = fused_planner.materialize(plan3,
                                          QueryContext()).print_tree()
        assert "MeshReduceExec" in tree3
        _assert_biteq(_run(fused_planner, ms, self.Q), oracle,
                      "post-split fused")
