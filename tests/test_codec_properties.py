"""Property-sweep codec tests (reference test-strategy analog:
memory/src/test/.../format/EncodingPropertiesTest.scala — ScalaCheck
round-trips over generated inputs).  A seeded matrix of data shapes ×
codecs asserts (a) bit-exact round-trips through the PYTHON
implementations, (b) byte-identical blobs from the C++ batch encoders
(wire parity: a reader must never care which side encoded), and (c)
bit-exact decodes through BOTH decoders for every blob."""

import numpy as np
import pytest

from filodb_tpu import native
from filodb_tpu.codecs import deltadelta, doublecodec

SEEDS = range(12)

HAVE_NATIVE = native.enable()


def _py(fn, *args):
    """Run a codec call with the pure-Python implementation."""
    native.disable()
    try:
        return fn(*args)
    finally:
        if HAVE_NATIVE:
            native.enable()


def _double_shapes(rng, n):
    """Generators spanning the codec's wire forms: delta2-integral,
    Gorilla gauge, NibblePack noise, RAW incompressible, NaN gaps,
    extremes."""
    yield "const", np.full(n, 42.5)
    yield "integral-walk", np.cumsum(
        rng.integers(-500, 500, size=n)).astype(np.float64)
    yield "gauge-walk", np.round(np.cumsum(rng.normal(0, 1, n)) * 8) / 8
    yield "iid-noise", rng.random(n)
    v = np.cumsum(rng.random(n))
    v[rng.random(n) < 0.2] = np.nan
    yield "nan-gaps", v
    yield "extremes", rng.choice(
        [0.0, -0.0, 1e308, -1e308, 5e-324, np.nan], size=n)


def _ll_shapes(rng, n):
    base = 1_700_000_000_000
    yield "regular-ts", base + np.arange(n, dtype=np.int64) * 10_000
    yield "jitter-ts", base + np.arange(n, dtype=np.int64) * 10_000 \
        + rng.integers(-50, 50, size=n)
    yield "random-ll", rng.integers(-2**40, 2**40, size=n)
    yield "counter", np.cumsum(rng.integers(0, 1000, size=n))


@pytest.mark.parametrize("seed", SEEDS)
def test_double_roundtrip_and_wire_parity(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 700))
    for name, vals in _double_shapes(rng, n):
        vals = np.asarray(vals, np.float64)
        blob = _py(doublecodec.encode, vals)
        got = _py(doublecodec.decode, blob)
        np.testing.assert_array_equal(
            got.view(np.uint64), vals.view(np.uint64),
            err_msg=f"python roundtrip {name} seed={seed}")
        if not HAVE_NATIVE:
            continue
        # C++ encoder must emit the identical wire bytes...
        cblob = doublecodec.encode_batch([vals])[0]
        assert cblob == blob, f"wire divergence {name} seed={seed}"
        # ...and the C++-hooked decoder must read it bit-exactly
        cvals = doublecodec.decode(blob)
        np.testing.assert_array_equal(
            np.asarray(cvals).view(np.uint64), vals.view(np.uint64),
            err_msg=f"native decode {name} seed={seed}")


@pytest.mark.parametrize("seed", SEEDS)
def test_longlong_roundtrip_and_wire_parity(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(1, 700))
    for name, vals in _ll_shapes(rng, n):
        vals = np.asarray(vals, np.int64)
        blob = _py(deltadelta.encode, vals)
        got = _py(deltadelta.decode, blob)
        np.testing.assert_array_equal(got, vals,
                                      err_msg=f"{name} seed={seed}")
        assert deltadelta.num_values(blob) == n
        if not HAVE_NATIVE:
            continue
        cblob = deltadelta.encode_batch([vals])[0]
        assert cblob == blob, f"wire divergence {name} seed={seed}"
        cvals = deltadelta.decode(blob)
        np.testing.assert_array_equal(np.asarray(cvals), vals,
                                      err_msg=f"native {name} seed={seed}")
