"""Property-sweep codec tests (reference test-strategy analog:
memory/src/test/.../format/EncodingPropertiesTest.scala — ScalaCheck
round-trips over generated inputs).  A seeded matrix of data shapes ×
codecs asserts (a) bit-exact round-trips through the PYTHON
implementations, (b) byte-identical blobs from the C++ batch encoders
(wire parity: a reader must never care which side encoded), and (c)
bit-exact decodes through BOTH decoders for every blob."""

import numpy as np
import pytest

from filodb_tpu import native
from filodb_tpu.codecs import deltadelta, doublecodec

SEEDS = range(12)

HAVE_NATIVE = native.enable()


def _py(fn, *args):
    """Run a codec call with the pure-Python implementation."""
    native.disable()
    try:
        return fn(*args)
    finally:
        if HAVE_NATIVE:
            native.enable()


def _double_shapes(rng, n):
    """Generators spanning the codec's wire forms: delta2-integral,
    Gorilla gauge, NibblePack noise, RAW incompressible, NaN gaps,
    extremes."""
    yield "const", np.full(n, 42.5)
    yield "integral-walk", np.cumsum(
        rng.integers(-500, 500, size=n)).astype(np.float64)
    yield "gauge-walk", np.round(np.cumsum(rng.normal(0, 1, n)) * 8) / 8
    yield "iid-noise", rng.random(n)
    v = np.cumsum(rng.random(n))
    v[rng.random(n) < 0.2] = np.nan
    yield "nan-gaps", v
    yield "extremes", rng.choice(
        [0.0, -0.0, 1e308, -1e308, 5e-324, np.nan], size=n)


def _ll_shapes(rng, n):
    base = 1_700_000_000_000
    yield "regular-ts", base + np.arange(n, dtype=np.int64) * 10_000
    yield "jitter-ts", base + np.arange(n, dtype=np.int64) * 10_000 \
        + rng.integers(-50, 50, size=n)
    yield "random-ll", rng.integers(-2**40, 2**40, size=n)
    yield "counter", np.cumsum(rng.integers(0, 1000, size=n))


@pytest.mark.parametrize("seed", SEEDS)
def test_double_roundtrip_and_wire_parity(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 700))
    for name, vals in _double_shapes(rng, n):
        vals = np.asarray(vals, np.float64)
        blob = _py(doublecodec.encode, vals)
        got = _py(doublecodec.decode, blob)
        np.testing.assert_array_equal(
            got.view(np.uint64), vals.view(np.uint64),
            err_msg=f"python roundtrip {name} seed={seed}")
        if not HAVE_NATIVE:
            continue
        # C++ encoder must emit the identical wire bytes...
        cblob = doublecodec.encode_batch([vals])[0]
        assert cblob == blob, f"wire divergence {name} seed={seed}"
        # ...and the C++-hooked decoder must read it bit-exactly
        cvals = doublecodec.decode(blob)
        np.testing.assert_array_equal(
            np.asarray(cvals).view(np.uint64), vals.view(np.uint64),
            err_msg=f"native decode {name} seed={seed}")


@pytest.mark.parametrize("seed", SEEDS)
def test_histogram_roundtrip(seed):
    from filodb_tpu.codecs import histcodec
    from filodb_tpu.core.histogram import CustomBuckets, GeometricBuckets

    rng = np.random.default_rng(2000 + seed)
    nrows = int(rng.integers(1, 160))
    nb = int(rng.integers(2, 64))
    schemes = [GeometricBuckets(float(rng.uniform(0.5, 4)),
                                float(rng.uniform(1.5, 3)), nb),
               CustomBuckets(np.sort(np.concatenate(
                   [rng.uniform(0.1, 1e4, nb - 1), [np.inf]])))]
    for buckets in schemes:
        incr = rng.integers(0, 20, (nrows, nb))
        rows = np.cumsum(np.cumsum(incr, axis=1), axis=0).astype(np.int64)
        if nrows > 4 and rng.random() < 0.5:
            cut = nrows // 2          # counter reset mid-stream
            rows[cut:] = np.cumsum(np.cumsum(
                rng.integers(0, 20, (nrows - cut, nb)), axis=1),
                axis=0)
        b2, rows2 = histcodec.decode(histcodec.encode(buckets, rows))
        assert b2 == buckets
        np.testing.assert_array_equal(rows2, rows, err_msg=f"seed={seed}")
        assert histcodec.num_values(histcodec.encode(buckets, rows)) \
            == nrows


@pytest.mark.parametrize("seed", SEEDS)
def test_string_and_nbit_roundtrip(seed):
    from filodb_tpu.codecs import strcodec

    rng = np.random.default_rng(3000 + seed)
    n = int(rng.integers(1, 400))
    # utf8 / dict form: low- and high-cardinality mixes, empty strings,
    # multi-byte codepoints
    alphabet = ["", "a", "pod-1", "νερό", "x" * 50,
                *(f"inst-{i}" for i in range(8))]
    strings = [alphabet[i] for i in rng.integers(0, len(alphabet), n)]
    blob = strcodec.encode_utf8(strings)
    got = [s.decode("utf-8") for s in strcodec.decode_utf8(blob)]
    assert got == strings, f"seed={seed}"
    # nbit ints across width classes
    for maxv in (1, 15, 255, 4095, 2**20):
        vals = rng.integers(0, maxv + 1, n).astype(np.int64)
        got_v = strcodec.decode_nbit(strcodec.encode_nbit(vals))
        np.testing.assert_array_equal(got_v[:n], vals,
                                      err_msg=f"maxv={maxv} seed={seed}")


@pytest.mark.parametrize("seed", SEEDS)
def test_longlong_roundtrip_and_wire_parity(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(1, 700))
    for name, vals in _ll_shapes(rng, n):
        vals = np.asarray(vals, np.int64)
        blob = _py(deltadelta.encode, vals)
        got = _py(deltadelta.decode, blob)
        np.testing.assert_array_equal(got, vals,
                                      err_msg=f"{name} seed={seed}")
        assert deltadelta.num_values(blob) == n
        if not HAVE_NATIVE:
            continue
        cblob = deltadelta.encode_batch([vals])[0]
        assert cblob == blob, f"wire divergence {name} seed={seed}"
        cvals = deltadelta.decode(blob)
        np.testing.assert_array_equal(np.asarray(cvals), vals,
                                      err_msg=f"native {name} seed={seed}")
