"""Codec round-trip tests (model: the reference's EncodingPropertiesTest /
RealTimeseriesEncodingTest, memory/src/test — exhaustive round-trips over
random + realistic data)."""

import numpy as np
import pytest

from filodb_tpu.codecs import deltadelta, doublecodec, histcodec, nibblepack, strcodec
from filodb_tpu.codecs.wire import WireType
from filodb_tpu.core.histogram import CustomBuckets, GeometricBuckets

rng = np.random.default_rng(42)


class TestNibblePack:
    def test_zigzag_roundtrip(self):
        v = rng.integers(-(2**62), 2**62, 1000, dtype=np.int64)
        assert np.array_equal(nibblepack.zigzag_decode(nibblepack.zigzag_encode(v)), v)
        small = np.array([0, -1, 1, -2, 2], dtype=np.int64)
        assert np.array_equal(nibblepack.zigzag_encode(small),
                              np.array([0, 1, 2, 3, 4], dtype=np.uint64))

    def test_zeros(self):
        v = np.zeros(16, dtype=np.uint64)
        packed = nibblepack.pack(v)
        assert len(packed) == 2  # one bitmask byte per group of 8
        out, _ = nibblepack.unpack(packed, 16)
        assert np.array_equal(out, v)

    def test_doc_example(self):
        # doc/compression.md example: two 3-nibble values sharing shift
        v = np.array([0x0000_0000_0012_3000, 0x0000_0000_0045_6000], dtype=np.uint64)
        packed = nibblepack.pack(v)
        # bitmask=0b11, header: trailing=3, nibbles=3 -> (3-1)<<4 | 3 = 0x23
        assert packed[0] == 0b00000011
        assert packed[1] == 0x23
        assert packed[2:5] == bytes([0x23, 0x61, 0x45])
        out, _ = nibblepack.unpack(packed, 2)
        assert np.array_equal(out, v)

    @pytest.mark.parametrize("n", [1, 7, 8, 9, 63, 64, 100])
    def test_random_roundtrip(self, n):
        for scale in (1, 2**16, 2**40, 2**63):
            v = rng.integers(0, scale, n, dtype=np.uint64)
            out, end = nibblepack.unpack(nibblepack.pack(v), n)
            assert np.array_equal(out, v)

    def test_packed_end(self):
        v = rng.integers(0, 2**30, 50, dtype=np.uint64)
        packed = nibblepack.pack(v)
        assert nibblepack.packed_end(packed, 50) == len(packed)

    def test_sparse(self):
        v = np.zeros(64, dtype=np.uint64)
        v[3] = 12345
        v[40] = 2**50
        out, _ = nibblepack.unpack(nibblepack.pack(v), 64)
        assert np.array_equal(out, v)


class TestDeltaDelta:
    def test_regular_timestamps_collapse_to_const(self):
        ts = np.arange(0, 720 * 10_000, 10_000, dtype=np.int64) + 1_600_000_000_000
        blob = deltadelta.encode(ts)
        assert blob[0] == WireType.CONST_LONG
        assert len(blob) == 21
        assert np.array_equal(deltadelta.decode(blob), ts)

    def test_jittery_timestamps(self):
        ts = np.cumsum(rng.integers(9_000, 11_000, 720)).astype(np.int64)
        blob = deltadelta.encode(ts)
        assert np.array_equal(deltadelta.decode(blob), ts)
        assert len(blob) < 8 * 720  # beats raw encoding

    def test_counter(self):
        v = np.cumsum(rng.integers(0, 100, 500)).astype(np.int64)
        assert np.array_equal(deltadelta.decode(deltadelta.encode(v)), v)

    def test_negative_and_random(self):
        v = rng.integers(-(2**40), 2**40, 300, dtype=np.int64)
        assert np.array_equal(deltadelta.decode(deltadelta.encode(v)), v)

    def test_empty_and_single(self):
        assert len(deltadelta.decode(deltadelta.encode(np.array([], dtype=np.int64)))) == 0
        one = np.array([42], dtype=np.int64)
        assert np.array_equal(deltadelta.decode(deltadelta.encode(one)), one)

    def test_num_values(self):
        v = np.arange(99, dtype=np.int64)
        assert deltadelta.num_values(deltadelta.encode(v)) == 99


class TestDoubleCodec:
    def test_integral_doubles_use_delta2(self):
        v = np.cumsum(rng.integers(0, 50, 400)).astype(np.float64)
        blob = doublecodec.encode(v)
        assert blob[0] == WireType.DELTA2_DOUBLE
        assert np.array_equal(doublecodec.decode(blob), v)

    def test_const(self):
        v = np.full(100, 3.5)
        blob = doublecodec.encode(v)
        assert blob[0] == WireType.CONST_DOUBLE
        assert np.array_equal(doublecodec.decode(blob), v)

    def test_gauge_roundtrip_bitexact(self):
        v = rng.normal(100, 15, 500)
        out = doublecodec.decode(doublecodec.encode(v))
        assert np.array_equal(out.view(np.uint64), v.view(np.uint64))

    def test_nan_sentinels_survive(self):
        v = rng.normal(0, 1, 64)
        v[[3, 17, 50]] = np.nan
        out = doublecodec.decode(doublecodec.encode(v))
        assert np.array_equal(np.isnan(out), np.isnan(v))
        assert np.array_equal(out[~np.isnan(v)], v[~np.isnan(v)])

    def test_num_values(self):
        v = rng.normal(0, 1, 123)
        assert doublecodec.num_values(doublecodec.encode(v)) == 123

    def test_compression_on_slowly_varying(self):
        # Gorilla-style XOR should beat raw on realistic gauges
        v = 100.0 + np.cumsum(rng.normal(0, 0.01, 1000))
        v = np.round(v, 2)
        blob = doublecodec.encode(v)
        assert len(blob) < 8 * 1000

    def test_gorilla_roundtrip_bitexact(self):
        """The SoA Gorilla stream (zero-bitmap + 12-bit windows +
        sig-bit plane) must round-trip every bit pattern."""
        cases = [
            np.repeat(rng.normal(40, 5, 13), rng.integers(5, 40, 13)),
            np.concatenate([[np.nan, np.inf, -np.inf, -0.0, 0.0],
                            rng.normal(0, 1, 59)]),
            np.full(100, 7.25) + (np.arange(100) % 3 == 0) * 0.5,
            rng.normal(1e-300, 1e-300, 77),
        ]
        for v in cases:
            v = np.asarray(v, np.float64)
            blob = doublecodec.encode(v)
            out = doublecodec.decode(blob)
            assert np.array_equal(out.view(np.uint64), v.view(np.uint64))

    def test_gorilla_wire_chosen_on_repetitive_gauges(self):
        """Flat-with-changes gauges (the Gorilla paper's production
        shape) must select the bit-level stream and land >=2x."""
        r = np.random.default_rng(42)     # own stream: the gorilla-vs-
        # nibblepack size race is data-dependent near the margin
        v = (np.repeat(r.normal(40, 5, 60),
                       r.integers(100, 250, 60))[:5000] + 0.125)
        blob = doublecodec.encode(v)
        assert blob[0] == WireType.GORILLA_DOUBLE
        assert len(blob) * 2 < 8 * len(v)
        assert np.array_equal(doublecodec.decode(blob).view(np.uint64),
                              v.view(np.uint64))
        assert doublecodec.num_values(blob) == len(v)

    def test_xor_nibblepack_still_wins_on_noise(self):
        """IID noise is XOR-incompressible at bit level; the selector
        must keep the NibblePack form there."""
        v = rng.normal(50, 10, 4096)
        blob = doublecodec.encode(v)
        assert blob[0] == WireType.XOR_DOUBLE
        assert np.array_equal(doublecodec.decode(blob).view(np.uint64),
                              v.view(np.uint64))


class TestHistCodec:
    def test_roundtrip_geometric(self):
        buckets = GeometricBuckets(2.0, 2.0, 16)
        # cumulative increasing counters per bucket
        incr = rng.integers(0, 10, (100, 16))
        rows = np.cumsum(np.cumsum(incr, axis=1), axis=0).astype(np.int64)
        blob = histcodec.encode(buckets, rows)
        b2, rows2 = histcodec.decode(blob)
        assert b2 == buckets
        assert np.array_equal(rows2, rows)
        assert histcodec.num_values(blob) == 100

    def test_roundtrip_custom_le(self):
        buckets = CustomBuckets(np.array([0.5, 1, 2.5, 5, 10, np.inf]))
        rows = np.cumsum(rng.integers(0, 5, (40, 6)), axis=1).astype(np.int64)
        rows = np.cumsum(rows, axis=0)
        b2, rows2 = histcodec.decode(histcodec.encode(buckets, rows))
        assert b2 == buckets
        assert np.array_equal(rows2, rows)

    def test_compression_factor(self):
        # doc/compression.md claims ~50x vs bucket-per-series Prom model for
        # 64-bucket histograms; assert a strong factor on sparse data (idle
        # histograms collapse even further)
        buckets = GeometricBuckets(1.0, 2.0, 64)
        incr = np.zeros((128, 64), dtype=np.int64)
        incr[:, 10] = 1
        rows = np.cumsum(np.cumsum(incr, axis=1), axis=0)
        blob = histcodec.encode(buckets, rows)
        prom_model_bytes = 128 * 64 * 16  # ts+value per bucket-series
        assert prom_model_bytes / len(blob) > 20
        idle = np.repeat(rows[:1], 128, axis=0)
        idle_blob = histcodec.encode(buckets, idle)
        assert prom_model_bytes / len(idle_blob) > 50

    def test_counter_reset_mid_stream(self):
        buckets = GeometricBuckets(1.0, 2.0, 8)
        rows = np.cumsum(np.cumsum(rng.integers(0, 4, (20, 8)), axis=1), axis=0)
        rows[10:] = np.cumsum(np.cumsum(rng.integers(0, 4, (10, 8)), axis=1), axis=0)
        rows = rows.astype(np.int64)
        _, rows2 = histcodec.decode(histcodec.encode(buckets, rows))
        assert np.array_equal(rows2, rows)


class TestStrCodec:
    def test_utf8_dense(self):
        strs = [b"hello", b"", "wörld".encode(), b"x" * 300]
        blob = strcodec.encode_utf8(strs)
        assert strcodec.decode_utf8(blob) == strs

    def test_dict_encoding_kicks_in(self):
        strs = [b"api", b"web", b"api", b"db"] * 10
        blob = strcodec.encode_utf8(strs)
        assert blob[0] == WireType.DICT_UTF8
        assert strcodec.decode_utf8(blob) == strs
        dense = strcodec.encode_utf8_dense(strs)
        assert len(blob) < len(dense)

    @pytest.mark.parametrize("maxv", [1, 3, 15, 255, 65535, 2**31])
    def test_nbit(self, maxv):
        v = rng.integers(0, maxv + 1, 101, dtype=np.uint32)
        out = strcodec.decode_nbit(strcodec.encode_nbit(v))
        assert np.array_equal(out, v)


class TestReviewRegressions:
    """Regressions from verification/review probes."""

    def test_wrong_wire_type_raises_valueerror(self):
        blob = doublecodec.encode(np.array([1.5, 2.5]))
        with pytest.raises(ValueError):
            deltadelta.decode(blob)

    def test_int64_extremes(self):
        v = np.array([np.iinfo(np.int64).min, 0, np.iinfo(np.int64).max], dtype=np.int64)
        assert np.array_equal(deltadelta.decode(deltadelta.encode(v)), v)

    def test_negative_zero_keeps_sign_bit(self):
        v = np.array([0.0, -0.0, 1.0])
        out = doublecodec.decode(doublecodec.encode(v))
        assert np.array_equal(np.signbit(out), np.signbit(v))

    def test_huge_finite_doubles_no_warning(self):
        import warnings
        v = np.array([1e300, 2e300])
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = doublecodec.decode(doublecodec.encode(v))
        assert np.array_equal(out, v)
