"""Compressed-resident equivalence: the fused on-device XOR-class
decode (ops/grid.py rate_grid_packed / rate_grid_grouped_packed) must be
bit-identical to the CPU codec decode (codecs/xorgrid.py unpack_vals)
and agree with the decoded-plane kernels across the layout's edge cases
— NaN payloads, constant runs, sign flips, partial final tiles, mixed
classes, promote/pad alignment.  Pallas runs in interpret mode so the
whole sweep executes in CPU CI (ISSUE 3 satellite)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from filodb_tpu.codecs.xorgrid import (LANE_BLOCK, UNPADDED_MAX, pack_vals,
                                       unpack_vals)
from filodb_tpu.ops.grid import (GridQuery, packed_width, rate_grid_grouped,
                                 rate_grid_grouped_packed, rate_grid_packed,
                                 rate_grid_ref)

STEP = 60_000


def _counters(rng, B, L, dtype=np.float32):
    """Integer-valued counters with a pinned f32 exponent: residuals
    provably fit 16 bits (see bench.py gen_packed)."""
    start = (2 ** 23 + 128 * rng.integers(0, 2 ** 15, L)).astype(dtype)
    inc = 128 * rng.integers(1, 8, (B, L))
    return (start[None, :] + np.cumsum(inc, axis=0)).astype(dtype)


def _edge_plane(rng, B, L):
    """A plane stressing every classification edge case at once."""
    v = np.empty((B, L), np.float32)
    n = L // 8
    v[:, :n] = 5.0                                      # constant run
    v[:, n:2 * n] = np.where(np.arange(B)[:, None] % 2 == 0,
                             1.5, -1.5)                 # sign flips
    # NaN payload bits must survive decode bit-for-bit
    pay = np.frombuffer(np.uint32(0x7fc01dea).tobytes(),
                        dtype=np.float32)[0]
    v[:, 2 * n:3 * n] = pay
    v[:, 3 * n:4 * n] = np.nan                          # all-NaN lanes
    v[:, 4 * n:5 * n] = _counters(rng, B, n)            # narrow class
    v[:, 5 * n:6 * n] = rng.random((B, n)) * 100        # incompressible
    v[:, 6 * n:7 * n] = _counters(rng, B, n)
    # partial fill: leading + trailing NaN around a counter run
    v[:, 7 * n:] = _counters(rng, B, L - 7 * n)
    v[:B // 4, 7 * n:] = np.nan
    v[-B // 4:, 7 * n:] = np.nan
    return v


class TestPackRoundtrip:
    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("L", [256, 137, 129, 1024])
    def test_edge_cases_bit_identical(self, seed, L):
        """Seeded sweep: whatever mix of classes/pads/promotions the
        aligner picks, the CPU decode reproduces the input bits."""
        rng = np.random.default_rng(seed)
        v = _edge_plane(rng, 64, L)
        pk = pack_vals(v)
        if pk is None:
            pytest.skip("mix did not pay at this width")
        out = unpack_vals(pk)
        np.testing.assert_array_equal(out.view(np.uint32),
                                      v.view(np.uint32))

    def test_f64_roundtrip_bit_identical(self):
        rng = np.random.default_rng(9)
        v = (1_000_000 + np.cumsum(rng.integers(-500, 500, (128, 192)),
                                   axis=0)).astype(np.float64)
        v[:, :40] = np.nan
        pk = pack_vals(v)
        assert pk is not None
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint64),
                                      v.view(np.uint64))

    def test_partial_final_tile_stays_unpadded(self):
        """A narrow class plane (< LANE_BLOCK) may skip alignment; the
        decode must still be exact and the footprint must not balloon."""
        rng = np.random.default_rng(2)
        v = np.full((128, 128), np.nan, np.float32)
        v[:, :6] = (rng.random((128, 6)).astype(np.float32) + 1) * 100
        pk = pack_vals(v)
        assert pk is not None
        assert pk.planes["raw"].shape[1] == 6          # unpadded tail
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))

    def test_alignment_invariant(self):
        """Every class plane is lane-block aligned OR narrow enough for
        a whole-plane kernel block (the encode-side guarantee the fused
        kernels rely on)."""
        for seed in range(6):
            rng = np.random.default_rng(seed)
            v = _edge_plane(rng, 64, 512)
            pk = pack_vals(v)
            if pk is None:
                continue
            for key in ("p8", "p16", "raw"):
                p = pk.planes.get(key)
                if p is None:
                    continue
                n = p.shape[1]
                assert n % LANE_BLOCK == 0 or n <= UNPADDED_MAX, (key, n)

    def test_min_width_forces_single_identity_plane(self):
        """The bench's group-contiguity contract: class-16-guaranteed
        counters with min_width=16 pack as ONE p16 plane in identity
        lane order."""
        rng = np.random.default_rng(3)
        L = 512
        v = _counters(rng, 59, L)
        v[:, 100:140] = np.nan                    # padding lanes
        pk = pack_vals(v, min_width=16)
        assert pk.planes["p16"].shape[1] == L
        assert pk.planes["raw"].shape[1] == 0
        assert (pk.inv == np.arange(L)).all()
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))


def _pack_dev(v, phase=None, **kw):
    pk = pack_vals(v, phase=phase, **kw)
    assert pk is not None
    np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                  v.view(np.uint32))
    return pk, {k: jnp.asarray(a) for k, a in pk.planes.items()}


class TestFusedKernelEquivalence:
    """rate_grid_packed / rate_grid_grouped_packed in interpret mode vs
    the decoded-plane oracle kernels."""

    @pytest.mark.parametrize("row0", [0, 3, 9])
    def test_phase_rate_matches_ref(self, row0):
        rng = np.random.default_rng(11)
        B, L = 64, 512
        v = _counters(rng, B, L)
        v[:, 200:230] = np.nan
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase)
        T, K = 20, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=row0,
                                          interpret=True,
                                          use_phase=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(
            None, jnp.asarray(v[row0:row0 + T + K - 1]), 0, q,
            phase=phase))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=2e-5)

    @pytest.mark.parametrize("op", ["sum", "max", "count", "last"])
    def test_free_ops_match_ref(self, op):
        """TS_FREE ops over a MIXED-class pack (p8 + p16 + raw planes),
        including the non-dense general path with NaN holes."""
        rng = np.random.default_rng(12)
        B, L = 64, 512
        v = _edge_plane(rng, B, L)
        pk, dev = _pack_dev(v)
        T, K = 12, 4
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op=op,
                      is_rate=False, dense=False)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=2,
                                          interpret=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(None,
                                       jnp.asarray(v[2:2 + T + K - 1]),
                                       0, q))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=1e-6)

    def test_grouped_packed_matches_grouped(self):
        """The fully fused grouped kernel (the north-star variant) vs
        the decoded-plane grouped phase kernel: identical partials."""
        rng = np.random.default_rng(13)
        B, L, GL = 59, 1024, 128
        v = _counters(rng, B, L)
        v[:, 500:520] = np.nan
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase, min_width=16)
        assert (pk.inv == np.arange(L)).all()
        T, K = 20, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        s_pk, c_pk = rate_grid_grouped_packed(dev, 0, q, group_lanes=GL,
                                              interpret=True)
        s_ph, c_ph = rate_grid_grouped(None, jnp.asarray(v), 0, q,
                                       group_lanes=GL, interpret=True,
                                       phase=phase)
        np.testing.assert_array_equal(np.asarray(c_pk), np.asarray(c_ph))
        np.testing.assert_allclose(np.asarray(s_pk), np.asarray(s_ph),
                                   rtol=1e-6)

    def test_packed_width_and_validation(self):
        rng = np.random.default_rng(14)
        v = _counters(rng, 64, 256)
        pk, dev = _pack_dev(v, min_width=16)
        assert packed_width(dev) == 256
        q = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, dense=True)
        with pytest.raises(ValueError, match="rows"):
            rate_grid_packed(dev, 0, q, row0=60, interpret=True,
                             use_phase=True)
        qbad = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, op="rate",
                         dense=True)
        with pytest.raises(ValueError, match="ts plane"):
            rate_grid_packed(dev, 0, qbad, interpret=True,
                             use_phase=False)

    def test_grouped_packed_rejects_padded_packs(self):
        """Alignment-pad lanes decode to finite 0.0 series; with no
        group map to drop them the fused grouped kernel would count
        them as live — it must refuse such packs."""
        rng = np.random.default_rng(16)
        B, L = 64, 896
        v = _counters(rng, B, L)
        pk = pack_vals(v, min_width=16)
        # append 128 zero pad lanes to the class plane exactly as the
        # aligner would (zero residuals, zero meta -> constant 0.0)
        planes = dict(pk.planes)
        planes["p16"] = np.pad(planes["p16"], ((0, 0), (0, 128)))
        planes["m16"] = np.pad(planes["m16"], ((0, 0), (0, 128)))
        planes["z16"] = np.pad(planes["z16"], (0, 128))
        planes["first"] = np.pad(planes["first"], (0, 128))
        dev = {k: jnp.asarray(a) for k, a in planes.items()}
        assert packed_width(dev) == L + 128 > dev["inv"].shape[0]
        q = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, dense=True)
        with pytest.raises(ValueError, match="pad lanes"):
            rate_grid_grouped_packed(dev, 0, q, group_lanes=128,
                                     interpret=True)

    def test_banded_mxu_correction_matches_ref(self):
        """K-heavy phase shape (2T < rows) takes the banded one-matmul
        correction+delta path; the reference (roll-scan) oracle pins
        its semantics, counter resets included."""
        rng = np.random.default_rng(15)
        B, L = 64, 256
        v = _counters(rng, B, L)
        # inject counter resets: drop back near the exponent floor
        for lane in range(0, L, 7):
            r = int(rng.integers(5, B - 5))
            v[r:, lane] = v[r:, lane] - v[r, lane] + 2 ** 23
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase, min_width=16)
        T, K = 8, 40                        # 2T=16 < 47 rows needed
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=0,
                                          interpret=True,
                                          use_phase=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(None,
                                       jnp.asarray(v[:T + K - 1]), 0, q,
                                       phase=phase))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=2e-5)
