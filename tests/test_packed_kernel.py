"""Compressed-resident equivalence: the fused on-device XOR-class
decode (ops/grid.py rate_grid_packed / rate_grid_grouped_packed) must be
bit-identical to the CPU codec decode (codecs/xorgrid.py unpack_vals)
and agree with the decoded-plane kernels across the layout's edge cases
— NaN payloads, constant runs, sign flips, partial final tiles, mixed
classes, promote/pad alignment.  Pallas runs in interpret mode so the
whole sweep executes in CPU CI (ISSUE 3 satellite).

ISSUE 14 widens the sweep to the histogram bucket-plane substrate
(stride packs + hist_grid_grouped_packed / hist_quantile_grid_packed),
the generic columnar scan-filter-topK program, and the devicestore
mid-stream bucket-widening path (16 -> 20 buckets)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from filodb_tpu.codecs.xorgrid import (LANE_BLOCK, UNPADDED_MAX, pack_vals,
                                       unpack_vals)
from filodb_tpu.ops import histogram_ops
from filodb_tpu.ops.grid import (GridQuery, event_topk_grid_packed,
                                 hist_grid_grouped_packed,
                                 hist_quantile_grid_packed, packed_width,
                                 rate_grid_grouped, rate_grid_grouped_packed,
                                 rate_grid_packed, rate_grid_ref)

STEP = 60_000


def _counters(rng, B, L, dtype=np.float32):
    """Integer-valued counters with a pinned f32 exponent: residuals
    provably fit 16 bits (see bench.py gen_packed)."""
    start = (2 ** 23 + 128 * rng.integers(0, 2 ** 15, L)).astype(dtype)
    inc = 128 * rng.integers(1, 8, (B, L))
    return (start[None, :] + np.cumsum(inc, axis=0)).astype(dtype)


def _edge_plane(rng, B, L):
    """A plane stressing every classification edge case at once."""
    v = np.empty((B, L), np.float32)
    n = L // 8
    v[:, :n] = 5.0                                      # constant run
    v[:, n:2 * n] = np.where(np.arange(B)[:, None] % 2 == 0,
                             1.5, -1.5)                 # sign flips
    # NaN payload bits must survive decode bit-for-bit
    pay = np.frombuffer(np.uint32(0x7fc01dea).tobytes(),
                        dtype=np.float32)[0]
    v[:, 2 * n:3 * n] = pay
    v[:, 3 * n:4 * n] = np.nan                          # all-NaN lanes
    v[:, 4 * n:5 * n] = _counters(rng, B, n)            # narrow class
    v[:, 5 * n:6 * n] = rng.random((B, n)) * 100        # incompressible
    v[:, 6 * n:7 * n] = _counters(rng, B, n)
    # partial fill: leading + trailing NaN around a counter run
    v[:, 7 * n:] = _counters(rng, B, L - 7 * n)
    v[:B // 4, 7 * n:] = np.nan
    v[-B // 4:, 7 * n:] = np.nan
    return v


class TestPackRoundtrip:
    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("L", [256, 137, 129, 1024])
    def test_edge_cases_bit_identical(self, seed, L):
        """Seeded sweep: whatever mix of classes/pads/promotions the
        aligner picks, the CPU decode reproduces the input bits."""
        rng = np.random.default_rng(seed)
        v = _edge_plane(rng, 64, L)
        pk = pack_vals(v)
        if pk is None:
            pytest.skip("mix did not pay at this width")
        out = unpack_vals(pk)
        np.testing.assert_array_equal(out.view(np.uint32),
                                      v.view(np.uint32))

    def test_f64_roundtrip_bit_identical(self):
        rng = np.random.default_rng(9)
        v = (1_000_000 + np.cumsum(rng.integers(-500, 500, (128, 192)),
                                   axis=0)).astype(np.float64)
        v[:, :40] = np.nan
        pk = pack_vals(v)
        assert pk is not None
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint64),
                                      v.view(np.uint64))

    def test_partial_final_tile_stays_unpadded(self):
        """A narrow class plane (< LANE_BLOCK) may skip alignment; the
        decode must still be exact and the footprint must not balloon."""
        rng = np.random.default_rng(2)
        v = np.full((128, 128), np.nan, np.float32)
        v[:, :6] = (rng.random((128, 6)).astype(np.float32) + 1) * 100
        pk = pack_vals(v)
        assert pk is not None
        assert pk.planes["raw"].shape[1] == 6          # unpadded tail
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))

    def test_alignment_invariant(self):
        """Every class plane is lane-block aligned OR narrow enough for
        a whole-plane kernel block (the encode-side guarantee the fused
        kernels rely on)."""
        for seed in range(6):
            rng = np.random.default_rng(seed)
            v = _edge_plane(rng, 64, 512)
            pk = pack_vals(v)
            if pk is None:
                continue
            for key in ("p8", "p16", "raw"):
                p = pk.planes.get(key)
                if p is None:
                    continue
                n = p.shape[1]
                assert n % LANE_BLOCK == 0 or n <= UNPADDED_MAX, (key, n)

    def test_min_width_forces_single_identity_plane(self):
        """The bench's group-contiguity contract: class-16-guaranteed
        counters with min_width=16 pack as ONE p16 plane in identity
        lane order."""
        rng = np.random.default_rng(3)
        L = 512
        v = _counters(rng, 59, L)
        v[:, 100:140] = np.nan                    # padding lanes
        pk = pack_vals(v, min_width=16)
        assert pk.planes["p16"].shape[1] == L
        assert pk.planes["raw"].shape[1] == 0
        assert (pk.inv == np.arange(L)).all()
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))


def _pack_dev(v, phase=None, **kw):
    pk = pack_vals(v, phase=phase, **kw)
    assert pk is not None
    np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                  v.view(np.uint32))
    return pk, {k: jnp.asarray(a) for k, a in pk.planes.items()}


class TestFusedKernelEquivalence:
    """rate_grid_packed / rate_grid_grouped_packed in interpret mode vs
    the decoded-plane oracle kernels."""

    @pytest.mark.parametrize("row0", [0, 3, 9])
    def test_phase_rate_matches_ref(self, row0):
        rng = np.random.default_rng(11)
        B, L = 64, 512
        v = _counters(rng, B, L)
        v[:, 200:230] = np.nan
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase)
        T, K = 20, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=row0,
                                          interpret=True,
                                          use_phase=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(
            None, jnp.asarray(v[row0:row0 + T + K - 1]), 0, q,
            phase=phase))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=2e-5)

    @pytest.mark.parametrize("op", ["sum", "max", "count", "last"])
    def test_free_ops_match_ref(self, op):
        """TS_FREE ops over a MIXED-class pack (p8 + p16 + raw planes),
        including the non-dense general path with NaN holes."""
        rng = np.random.default_rng(12)
        B, L = 64, 512
        v = _edge_plane(rng, B, L)
        pk, dev = _pack_dev(v)
        T, K = 12, 4
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op=op,
                      is_rate=False, dense=False)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=2,
                                          interpret=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(None,
                                       jnp.asarray(v[2:2 + T + K - 1]),
                                       0, q))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=1e-6)

    def test_grouped_packed_matches_grouped(self):
        """The fully fused grouped kernel (the north-star variant) vs
        the decoded-plane grouped phase kernel: identical partials."""
        rng = np.random.default_rng(13)
        B, L, GL = 59, 1024, 128
        v = _counters(rng, B, L)
        v[:, 500:520] = np.nan
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase, min_width=16)
        assert (pk.inv == np.arange(L)).all()
        T, K = 20, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        s_pk, c_pk = rate_grid_grouped_packed(dev, 0, q, group_lanes=GL,
                                              interpret=True)
        s_ph, c_ph = rate_grid_grouped(None, jnp.asarray(v), 0, q,
                                       group_lanes=GL, interpret=True,
                                       phase=phase)
        np.testing.assert_array_equal(np.asarray(c_pk), np.asarray(c_ph))
        np.testing.assert_allclose(np.asarray(s_pk), np.asarray(s_ph),
                                   rtol=1e-6)

    def test_packed_width_and_validation(self):
        rng = np.random.default_rng(14)
        v = _counters(rng, 64, 256)
        pk, dev = _pack_dev(v, min_width=16)
        assert packed_width(dev) == 256
        q = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, dense=True)
        with pytest.raises(ValueError, match="rows"):
            rate_grid_packed(dev, 0, q, row0=60, interpret=True,
                             use_phase=True)
        qbad = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, op="rate",
                         dense=True)
        with pytest.raises(ValueError, match="ts plane"):
            rate_grid_packed(dev, 0, qbad, interpret=True,
                             use_phase=False)

    def test_grouped_packed_rejects_padded_packs(self):
        """Alignment-pad lanes decode to finite 0.0 series; with no
        group map to drop them the fused grouped kernel would count
        them as live — it must refuse such packs."""
        rng = np.random.default_rng(16)
        B, L = 64, 896
        v = _counters(rng, B, L)
        pk = pack_vals(v, min_width=16)
        # append 128 zero pad lanes to the class plane exactly as the
        # aligner would (zero residuals, zero meta -> constant 0.0)
        planes = dict(pk.planes)
        planes["p16"] = np.pad(planes["p16"], ((0, 0), (0, 128)))
        planes["m16"] = np.pad(planes["m16"], ((0, 0), (0, 128)))
        planes["z16"] = np.pad(planes["z16"], (0, 128))
        planes["first"] = np.pad(planes["first"], (0, 128))
        dev = {k: jnp.asarray(a) for k, a in planes.items()}
        assert packed_width(dev) == L + 128 > dev["inv"].shape[0]
        q = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, dense=True)
        with pytest.raises(ValueError, match="pad lanes"):
            rate_grid_grouped_packed(dev, 0, q, group_lanes=128,
                                     interpret=True)

    def test_event_topk_matches_ref(self):
        """Generic columnar scan-filter-topK over a MIXED-class pack:
        the packed-order contract composes garr through inv, filter
        column packed with a DIFFERENT layout composed via filt_pos."""
        rng = np.random.default_rng(21)
        B, L, G, k = 64, 512, 8, 3
        v = _edge_plane(rng, B, L)
        pk, dev = _pack_dev(v)
        fv = _counters(rng, B, L)
        pkf, devf = _pack_dev(fv, min_width=16)
        assert (pkf.inv == np.arange(L)).all()
        T, K = 12, 4
        qs = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op="sum",
                       is_rate=False, dense=False)
        ql = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op="last",
                      is_rate=False, dense=True)
        garr_orig = (np.arange(L) % G).astype(np.int32)
        # garr and filt_pos are in the VALUE pack's lane order
        npk = packed_width(dev)
        garr_pk = np.full(npk, G, np.int32)
        garr_pk[pk.inv] = garr_orig
        filt_pos = np.zeros(npk, np.int64)
        filt_pos[pk.inv] = pkf.inv          # value-pos -> filter-pos
        vals, idx = event_topk_grid_packed(
            dev, 0, qs, k, jnp.asarray(garr_pk), G,
            filt_packed=devf, filt_op="gt",
            filt_thresh=float(np.median(fv[B // 2])), filt_q=ql,
            filt_pos=jnp.asarray(filt_pos), interpret=True)
        # oracle: decoded-plane reference + numpy reduce + ranking
        sv = np.asarray(rate_grid_ref(None, jnp.asarray(v[:T + K - 1]),
                                      0, qs))
        sf = np.asarray(rate_grid_ref(None, jnp.asarray(fv[:T + K - 1]),
                                      0, ql))
        masked = np.where(sf > float(np.median(fv[B // 2])), sv, np.nan)
        fin = np.isfinite(masked)
        gs = np.zeros((G, T))
        gc = np.zeros((G, T))
        for c in range(L):
            g = garr_orig[c]
            gs[g] += np.where(fin[:, c], masked[:, c], 0.0)
            gc[g] += fin[:, c]
        ranked = np.where(gc > 0, gs, -np.inf)
        got_v, got_i = np.asarray(vals), np.asarray(idx)
        for t in range(T):
            order = np.argsort(-ranked[:, t], kind="stable")[:k]
            want = np.where(np.isfinite(ranked[order, t]),
                            ranked[order, t], np.nan)
            np.testing.assert_allclose(got_v[t], want, rtol=1e-5,
                                       equal_nan=True)
            live = np.isfinite(want)
            assert set(got_i[t][live]) == set(order[live])
            assert (got_i[t][~live] == -1).all()

    def test_event_topk_bottomk_and_bad_filter_op(self):
        rng = np.random.default_rng(22)
        B, L, G = 64, 256, 4
        v = _counters(rng, B, L)
        _pk, dev = _pack_dev(v, min_width=16)
        T, K = 8, 4
        qs = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op="sum",
                       is_rate=False, dense=True)
        garr = (np.arange(L) % G).astype(np.int32)
        vals, _ = event_topk_grid_packed(dev, 0, qs, 2,
                                         jnp.asarray(garr), G,
                                         interpret=True, largest=False)
        sv = np.asarray(rate_grid_ref(None, jnp.asarray(v[:T + K - 1]),
                                      0, qs))
        gs = np.zeros((G, T))
        for c in range(L):
            gs[garr[c]] += sv[:, c]
        want = np.sort(gs, axis=0)[:2].T
        np.testing.assert_allclose(np.sort(np.asarray(vals), axis=1),
                                   np.sort(want, axis=1), rtol=1e-5)
        with pytest.raises(ValueError, match="filter op"):
            event_topk_grid_packed(dev, 0, qs, 2, jnp.asarray(garr), G,
                                   filt_packed=dev, filt_op="contains",
                                   interpret=True)

    def test_event_topk_group_width_and_segment_paths_agree(self):
        """The three reduce formulations — banded group_width
        reshape-sum, one-hot MXU matmul, and the >_TOPK_ONEHOT_MAX_G
        segment_sum fallback (exercised with a genuinely large group
        space: sparse groups rank NaN) — must rank identically."""
        rng = np.random.default_rng(23)
        B, L, G = 64, 256, 8
        v = _counters(rng, B, L)
        _pk, dev = _pack_dev(v, min_width=16)
        T, K = 8, 4
        qs = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op="sum",
                       is_rate=False, dense=True)
        garr = (np.arange(L, dtype=np.int32) // (L // G))
        by_onehot = event_topk_grid_packed(
            dev, 0, qs, 3, jnp.asarray(garr), G, interpret=True)
        by_width = event_topk_grid_packed(
            dev, 0, qs, 3, None, G, interpret=True,
            group_width=L // G)
        # same lanes scattered into a 4096-group space (> the one-hot
        # cap -> segment_sum): occupied slots are g*512, so dividing
        # the winning indices by 512 must reproduce the small ranking
        by_segment = event_topk_grid_packed(
            dev, 0, qs, 3, jnp.asarray(garr * 512), 4096,
            interpret=True)
        np.testing.assert_allclose(np.asarray(by_width[0]),
                                   np.asarray(by_onehot[0]), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(by_width[1]),
                                      np.asarray(by_onehot[1]))
        np.testing.assert_allclose(np.asarray(by_segment[0]),
                                   np.asarray(by_onehot[0]), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(by_segment[1]) // 512,
                                      np.asarray(by_onehot[1]))
        with pytest.raises(ValueError, match="not both"):
            event_topk_grid_packed(dev, 0, qs, 3, jnp.asarray(garr), G,
                                   interpret=True, group_width=L // G)
        with pytest.raises(ValueError, match="group_width"):
            event_topk_grid_packed(dev, 0, qs, 3, None, G + 1,
                                   interpret=True, group_width=L // G)

    def test_banded_mxu_correction_matches_ref(self):
        """K-heavy phase shape (2T < rows) takes the banded one-matmul
        correction+delta path; the reference (roll-scan) oracle pins
        its semantics, counter resets included."""
        rng = np.random.default_rng(15)
        B, L = 64, 256
        v = _counters(rng, B, L)
        # inject counter resets: drop back near the exponent floor
        for lane in range(0, L, 7):
            r = int(rng.integers(5, B - 5))
            v[r:, lane] = v[r:, lane] - v[r, lane] + 2 ** 23
        phase = rng.integers(1, STEP, L).astype(np.int32)
        pk, dev = _pack_dev(v, phase=phase, min_width=16)
        T, K = 8, 40                        # 2T=16 < 47 rows needed
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        out = np.asarray(rate_grid_packed(dev, 0, q, row0=0,
                                          interpret=True,
                                          use_phase=True))[:, pk.inv]
        ref = np.asarray(rate_grid_ref(None,
                                       jnp.asarray(v[:T + K - 1]), 0, q,
                                       phase=phase))
        fin = np.isfinite(ref)
        assert (np.isfinite(out) == fin).all()
        np.testing.assert_allclose(out[fin], ref[fin], rtol=2e-5)


def _hist_plane(rng, B, n_series, hb, mixed=False):
    """[B, n_series*hb] bucket plane: column s*hb + j = series s's
    cumulative bucket j (the devicestore hist group-slot layout), all
    integer-valued with a pinned f32 exponent.  ``mixed`` adds all-NaN
    series and a raw-class (incompressible) series."""
    L = n_series * hb
    start = (2 ** 23 + 128 * rng.integers(0, 2 ** 15, L)).astype(np.float32)
    inc = 128 * rng.integers(1, 8, (B, L))
    v = (start[None, :] + np.cumsum(inc, axis=0)).astype(np.float32)
    if mixed and n_series >= 4:
        v[:, 0:hb] = np.nan                          # dead series
        v[:, hb:2 * hb] = rng.random((B, hb)).astype(np.float32) * 100
    phase = np.repeat(rng.integers(1, STEP, n_series), hb).astype(np.int32)
    return v, phase


class TestHistStridePack:
    """codecs/xorgrid.py stride packs: series-granular classification,
    bucket contiguity, bit-exact roundtrip (ISSUE 14 tentpole 1)."""

    @pytest.mark.parametrize("seed", range(3))
    @pytest.mark.parametrize("hb", [4, 16, 20])
    def test_roundtrip_and_series_contiguity(self, seed, hb):
        rng = np.random.default_rng(seed)
        nser = 37
        v, phase = _hist_plane(rng, 64, nser, hb, mixed=True)
        pk = pack_vals(v, phase=phase, stride=hb)
        if pk is None:
            pytest.skip("mix did not pay at this width")
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))
        # every series' hb columns are CONTIGUOUS in packed order, in
        # bucket order — the fused hist kernels' slicing contract
        for s in range(nser):
            pos = pk.inv[s * hb:(s + 1) * hb]
            assert (np.diff(pos) == 1).all(), (s, pos)

    def test_stride_must_divide_width(self):
        rng = np.random.default_rng(1)
        v, _ = _hist_plane(rng, 64, 4, 4)
        with pytest.raises(ValueError, match="stride"):
            pack_vals(v[:, :-1], stride=4)

    def test_stride_alignment_pads_never_split_series(self):
        """Misaligned class widths at stride > 1 must pad (zero lanes),
        never promote a partial series across classes."""
        rng = np.random.default_rng(2)
        hb = 20
        v, phase = _hist_plane(rng, 64, 33, hb, mixed=True)  # 660 cols
        pk = pack_vals(v, phase=phase, stride=hb)
        if pk is None:
            pytest.skip("did not pay")
        for key in ("p8", "p16", "raw"):
            p = pk.planes.get(key)
            if p is None:
                continue
            n = p.shape[1]
            assert n % LANE_BLOCK == 0 or n <= UNPADDED_MAX, (key, n)
        np.testing.assert_array_equal(unpack_vals(pk).view(np.uint32),
                                      v.view(np.uint32))


class TestHistFusedKernels:
    """hist_grid_grouped_packed / hist_quantile_grid_packed in
    interpret mode vs the decoded-plane reference + the shared
    hist-quantile math (ISSUE 14 tentpole 2)."""

    @pytest.mark.parametrize("hb,row0", [(4, 0), (8, 3), (20, 0)])
    def test_grouped_matches_ref(self, hb, row0):
        rng = np.random.default_rng(31)
        per, gh = 8, 4
        nser = per * gh
        v, phase = _hist_plane(rng, 64, nser, hb)
        v[:, 2 * hb:3 * hb] = np.nan               # one dead series
        pk = pack_vals(v, phase=phase, min_width=16, stride=hb)
        assert pk is not None and (pk.inv == np.arange(nser * hb)).all()
        dev = {k: jnp.asarray(a) for k, a in pk.planes.items()}
        T, K = 10, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        s, c = hist_grid_grouped_packed(dev, 0, q, hb,
                                        group_lanes=per * hb, row0=row0,
                                        interpret=True, use_phase=True)
        s, c = np.asarray(s), np.asarray(c)
        assert s.shape == (gh * hb, T)
        ref = np.asarray(rate_grid_ref(
            None, jnp.asarray(v[row0:row0 + T + K - 1]), 0, q,
            phase=phase))
        want = np.zeros((gh * hb, T), np.float32)
        wcnt = np.zeros((gh * hb, T), np.float32)
        for col in range(nser * hb):
            g, j = col // (per * hb), col % hb
            fin = np.isfinite(ref[:, col])
            want[g * hb + j] += np.where(fin, ref[:, col], 0.0)
            wcnt[g * hb + j] += fin
        np.testing.assert_allclose(s, want, rtol=2e-5)
        np.testing.assert_array_equal(c, wcnt)

    def test_quantile_matches_shared_math(self):
        rng = np.random.default_rng(32)
        hb, per, gh = 8, 16, 4
        v, phase = _hist_plane(rng, 64, per * gh, hb)
        pk = pack_vals(v, phase=phase, min_width=16, stride=hb)
        assert pk is not None
        dev = {k: jnp.asarray(a) for k, a in pk.planes.items()}
        T, K = 10, 5
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, is_rate=True,
                      dense=True)
        tops = np.concatenate([2.0 ** np.arange(hb - 1), [np.inf]])
        out = np.asarray(hist_quantile_grid_packed(
            dev, 0, jnp.asarray(tops), q, 0.99, hb,
            group_lanes=per * hb, interpret=True))
        s, _c = hist_grid_grouped_packed(dev, 0, q, hb,
                                         group_lanes=per * hb,
                                         interpret=True, use_phase=True)
        hist_sum = np.asarray(s).reshape(gh, hb, T).transpose(0, 2, 1)
        want = np.asarray(histogram_ops.hist_quantile(
            jnp.asarray(tops), jnp.asarray(hist_sum), 0.99))
        # the fused program inlines the grouped kernel under one jit;
        # XLA's reassociation shifts the f32 sums by ~1 ulp vs the
        # standalone call, which the interpolation divides amplify
        np.testing.assert_allclose(out, want, rtol=2e-5)

    def test_free_op_sum_over_time_no_phase(self):
        """TS_FREE hist shape (sum_over_time over buckets) takes the
        non-phase kernel branch."""
        rng = np.random.default_rng(33)
        hb, per, gh = 4, 8, 2
        v, phase = _hist_plane(rng, 64, per * gh, hb)
        pk = pack_vals(v, phase=phase, min_width=16, stride=hb)
        dev = {k: jnp.asarray(a) for k, a in pk.planes.items()}
        T, K = 10, 4
        q = GridQuery(nsteps=T, kbuckets=K, gstep_ms=STEP, op="sum",
                      is_rate=False, dense=True)
        s, c = hist_grid_grouped_packed(dev, 0, q, hb,
                                        group_lanes=per * hb,
                                        interpret=True, use_phase=False)
        ref = np.asarray(rate_grid_ref(None, jnp.asarray(v[:T + K - 1]),
                                       0, q))
        want = np.zeros((gh * hb, T), np.float32)
        for col in range(per * gh * hb):
            g, j = col // (per * hb), col % hb
            want[g * hb + j] += np.where(np.isfinite(ref[:, col]),
                                         ref[:, col], 0.0)
        np.testing.assert_allclose(np.asarray(s), want, rtol=2e-5)

    def test_rejects_misaligned_and_padded(self):
        rng = np.random.default_rng(34)
        hb = 4
        v, phase = _hist_plane(rng, 64, 32, hb)
        pk = pack_vals(v, phase=phase, min_width=16, stride=hb)
        dev = {k: jnp.asarray(a) for k, a in pk.planes.items()}
        q = GridQuery(nsteps=8, kbuckets=4, gstep_ms=STEP, dense=True)
        with pytest.raises(ValueError, match="multiple of"):
            hist_grid_grouped_packed(dev, 0, q, hb, group_lanes=30,
                                     interpret=True)
        padded = dict(pk.planes)
        padded["p16"] = np.pad(padded["p16"], ((0, 0), (0, 128)))
        padded["m16"] = np.pad(padded["m16"], ((0, 0), (0, 128)))
        padded["z16"] = np.pad(padded["z16"], (0, 128))
        padded["first"] = np.pad(padded["first"], (0, 128))
        devp = {k: jnp.asarray(a) for k, a in padded.items()}
        with pytest.raises(ValueError, match="pad lanes"):
            hist_grid_grouped_packed(devp, 0, q, hb, group_lanes=32,
                                     interpret=True)


class TestHistServingWidening:
    """Mid-stream bucket-count widening (16 -> 20 buckets) through the
    REAL serving path (devicestore.py hb re-probe): the cache disables
    on the widened chunk, re-probes the bucket scheme, and the packed
    fused path serves the widened layout with narrow rows edge-padded —
    equal to the host oracle."""

    def test_widening_16_to_20_reprobes_and_serves_packed(self, monkeypatch):
        from filodb_tpu.codecs import histcodec
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.core.histogram import GeometricBuckets
        from filodb_tpu.core.record import RecordBuilder, decode_container
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
        from filodb_tpu.core.storeconfig import StoreConfig
        from filodb_tpu.memstore import devicestore
        from filodb_tpu.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns
        from filodb_tpu.query.logical import RangeFunctionId as F

        monkeypatch.setattr(devicestore, "_PACKED_INTERPRET", True)
        monkeypatch.setattr(devicestore, "_PACKED_BROKEN", False)
        monkeypatch.setattr(devicestore.DeviceGridCache, "_val_dtype",
                            lambda self: np.float32)
        T0 = 1_600_000_000_000
        HSTEP = 10_000
        rng = np.random.default_rng(6)
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())

        def ingest(t0, rows, nb, off0):
            buckets = GeometricBuckets(2.0, 2.0, nb)
            b = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"],
                              DatasetOptions())
            for s in range(3):
                cum = np.zeros(nb, np.int64)
                for t in range(rows):
                    cum += 128 * rng.integers(1, 8, nb)
                    vals = 2 ** 23 + np.cumsum(cum)
                    blob = histcodec.encode_hist_value(buckets, vals)
                    b.add(t0 + t * HSTEP, (float(vals[-1]),
                                           float(vals[-1]), blob),
                          {"__name__": "lat", "inst": f"i{s}",
                           "_ws_": "w", "_ns_": "n"})
            for off, c in enumerate(b.containers()):
                shard.ingest(decode_container(c, DEFAULT_SCHEMAS),
                             off0 + off)
            shard.flush_all()

        ingest(T0, 48, 16, 0)
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("lat"))], 0, 2 ** 62)
        K = 4
        W = K * HSTEP
        steps0 = T0 + (K + 1) * HSTEP
        got = shard.scan_grid(res.part_ids, F.SUM_OVER_TIME, steps0, 20,
                              HSTEP, W)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        assert cache.hb == 16
        assert next(iter(cache._plan_memo.values())).packed is not None
        # widen mid-stream: 20-bucket rows arrive
        ingest(T0 + 48 * HSTEP, 48, 20, 100)
        # the first query over the widened span hits the 16-bucket probe
        # and disables (devicestore _build: bucket scheme widened); the
        # re-probe path must then serve hb=20 once the backoff clears
        steps1 = T0 + (48 + K + 1) * HSTEP
        shard.scan_grid(res.part_ids, F.SUM_OVER_TIME, steps1, 20,
                        HSTEP, W)
        cache.disabled_until_version = -1          # clear the backoff
        got2 = shard.scan_grid(res.part_ids, F.SUM_OVER_TIME, steps1, 20,
                               HSTEP, W)
        assert got2 is not None
        assert cache.hb == 20
        tags, vals, tops = got2
        assert vals.shape[2] == 20 and len(tops) == 20
        plan = next(iter(cache._plan_memo.values()))
        assert plan.packed is not None, "widened hist did not re-pack"
        assert not devicestore._PACKED_BROKEN
        # host oracle over the widened span
        end = steps1 + 19 * HSTEP
        t2, batch = shard.scan_batch(res.part_ids, steps1 - W, end)
        sr = StepRange(steps1, end, HSTEP)
        want = np.asarray(rangefns.apply_range_function(
            batch, sr, W, F.SUM_OVER_TIME))[:len(tags)]
        fin = np.isfinite(want)
        assert (np.isfinite(np.asarray(vals)) == fin).all()
        np.testing.assert_allclose(np.asarray(vals)[fin], want[fin],
                                   rtol=1e-5)
