"""Query engine tests: exec plans + transformers + aggregators over a real
in-process memstore (reference test pattern: direct ExecPlan construction
with InProcessPlanDispatcher, MultiSchemaPartitionsExecSpec,
AggrOverRangeVectorsSpec, BinaryJoinExecSpec — SURVEY.md §4)."""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore import TimeSeriesMemStore
from filodb_tpu.query.exec import (BinaryJoinExec, DistConcatExec, ExecContext,
                                   LabelValuesDistConcatExec, LabelValuesExec,
                                   MultiSchemaPartitionsExec, PartKeysExec,
                                   ReduceAggregateExec, ScalarBinaryOperationExec,
                                   ScalarFixedDoubleExec, SetOperatorExec,
                                   TimeScalarGeneratorExec)
from filodb_tpu.query.logical import (AggregationOperator, BinaryOperator,
                                      Cardinality, InstantFunctionId,
                                      MiscellaneousFunctionId, RangeFunctionId,
                                      ScalarFunctionId, SortFunctionId)
from filodb_tpu.query.model import PeriodicBatch, QueryContext, QueryError
from filodb_tpu.query.transformers import (AbsentFunctionMapper,
                                           AggregateMapReduce,
                                           AggregatePresenter,
                                           HistogramQuantileMapper,
                                           InstantVectorFunctionMapper,
                                           MiscellaneousFunctionMapper,
                                           PeriodicSamplesMapper,
                                           ScalarOperationMapper,
                                           SortFunctionMapper,
                                           StitchRvsMapper)
from tests import oracle
from tests.data import START_TS, counter_containers, gauge_containers, histogram_containers

MAX = np.iinfo(np.int64).max
STEP = 10_000


def eq(k, v):
    return ColumnFilter(k, Equals(v))


@pytest.fixture(scope="module")
def ms():
    store = TimeSeriesMemStore()
    cfg = StoreConfig(groups_per_shard=4, max_chunks_size=64,
                      batch_row_pad=32, batch_series_pad=4)
    for shard in (0, 1):
        store.setup("ds", DEFAULT_SCHEMAS, shard, cfg)
    # series 0..5 on shard 0, 6..11 on shard 1 (6 series each)
    for off, c in enumerate(gauge_containers(n_series=6, n_samples=120)):
        store.ingest("ds", 0, c, off)
    b2 = gauge_containers(n_series=6, n_samples=120, seed=43)
    # shift tags so shard 1 has different instances
    from filodb_tpu.core.record import RecordBuilder, decode_container
    from filodb_tpu.core.schemas import DatasetOptions
    rb = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    for c in b2:
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            tags = dict(rec.tags, instance=str(int(rec.tags["instance"]) + 6))
            rb.add(rec.timestamp, rec.values, tags)
    for off, c in enumerate(rb.containers()):
        store.ingest("ds", 1, c, off)
    for off, c in enumerate(counter_containers(n_series=3, n_samples=120)):
        store.ingest("ds", 0, c, 100 + off)
    for off, c in enumerate(histogram_containers(n_series=2, n_samples=60)):
        store.ingest("ds", 0, c, 200 + off)
    return store


@pytest.fixture()
def ctx(ms):
    return ExecContext(ms, QueryContext(query_id="t1"))


def leaf(metric, shard=0, start=START_TS, end=START_TS + 2_000_000):
    return MultiSchemaPartitionsExec("ds", shard, [eq("_metric_", metric)],
                                     start, end)


def grid(start=START_TS + 300_000, end=START_TS + 900_000):
    return dict(start_ms=start, step_ms=STEP, end_ms=end)


class TestLeafAndWindowing:
    def test_raw_scan(self, ctx):
        plan = leaf("heap_usage")
        res = plan.execute(ctx)
        assert len(res.batches) == 1
        raw = res.batches[0]
        assert len(raw.keys) == 6
        assert raw.batch.row_counts[:6].sum() == 6 * 120

    def test_periodic_rate_matches_oracle(self, ctx):
        g = grid()
        plan = leaf("http_requests_total")
        plan.add_transformer(PeriodicSamplesMapper(
            window_ms=60_000, function=RangeFunctionId.RATE, **g))
        res = plan.execute(ctx)
        b = res.batches[0]
        assert isinstance(b, PeriodicBatch)
        assert b.num_series == 3
        # oracle comparison on one series
        shard = ctx.memstore.get_shard("ds", 0)
        look = shard.lookup_partitions([eq("_metric_", "http_requests_total")],
                                       0, MAX)
        i = int(np.argwhere([t == b.keys[0] for t in
                             [shard.partitions[int(p)].tags
                              for p in look.part_ids]])[0][0])
        part = shard.partitions[int(look.part_ids[i])]
        ts, vals = part.read_range(0, MAX)
        expect = oracle.range_fn("rate", ts, vals, g["start_ms"], g["end_ms"],
                                 STEP, 60_000)
        np.testing.assert_allclose(b.np_values()[0], expect, rtol=1e-9,
                                   equal_nan=True)

    def test_instant_selector_default_lookback(self, ctx):
        plan = leaf("heap_usage")
        plan.add_transformer(PeriodicSamplesMapper(**grid()))
        res = plan.execute(ctx)
        b = res.batches[0]
        # dense data: every step has the last sample within 5m
        assert np.isfinite(b.np_values()).all()

    def test_offset(self, ctx):
        g = grid()
        p1 = leaf("heap_usage")
        p1.add_transformer(PeriodicSamplesMapper(
            window_ms=120_000, function=RangeFunctionId.SUM_OVER_TIME,
            offset_ms=60_000, **g))
        res1 = p1.execute(ctx)
        g2 = dict(g)
        g2["start_ms"] -= 60_000
        g2["end_ms"] -= 60_000
        p2 = leaf("heap_usage")
        p2.add_transformer(PeriodicSamplesMapper(
            window_ms=120_000, function=RangeFunctionId.SUM_OVER_TIME, **g2))
        res2 = p2.execute(ctx)
        np.testing.assert_allclose(res1.batches[0].np_values(),
                                   res2.batches[0].np_values(), equal_nan=True)
        # but reported at the unshifted grid
        assert res1.batches[0].steps.start == g["start_ms"]

    def test_sample_limit(self, ms):
        strict = ExecContext(ms, QueryContext(sample_limit=10))
        plan = leaf("heap_usage")
        plan.add_transformer(PeriodicSamplesMapper(**grid()))
        with pytest.raises(QueryError, match="limit"):
            plan.execute(strict)


class TestAggregation:
    def run_agg(self, ctx, op, params=(), by=(), without=(), metric="heap_usage",
                fn=RangeFunctionId.SUM_OVER_TIME):
        children = []
        for shard in (0, 1):
            p = leaf(metric, shard)
            p.add_transformer(PeriodicSamplesMapper(
                window_ms=60_000, function=fn, **grid()))
            p.add_transformer(AggregateMapReduce(op, params, by, without))
            children.append(p)
        root = ReduceAggregateExec(children, op, params)
        root.add_transformer(AggregatePresenter(op, params))
        return root.execute(ctx)

    def oracle_values(self, ctx, metric="heap_usage"):
        """[S, T] sum_over_time values across both shards + their keys."""
        out_keys, rows = [], []
        g = grid()
        for shard_num in (0, 1):
            shard = ctx.memstore.get_shard("ds", shard_num)
            look = shard.lookup_partitions([eq("_metric_", metric)], 0, MAX)
            for pid in look.part_ids:
                part = shard.partitions[int(pid)]
                ts, vals = part.read_range(0, MAX)
                rows.append(oracle.range_fn("sum_over_time", ts, vals,
                                            g["start_ms"], g["end_ms"], STEP,
                                            60_000))
                out_keys.append(part.tags)
        return out_keys, np.stack(rows)

    def test_sum_cross_shard(self, ctx):
        res = self.run_agg(ctx, AggregationOperator.SUM)
        keys, vals = self.oracle_values(ctx)
        expect = np.nansum(vals, axis=0)
        assert res.batches[0].num_series == 1
        np.testing.assert_allclose(res.batches[0].np_values()[0], expect,
                                   rtol=1e-9)

    def test_sum_by_ns(self, ctx):
        res = self.run_agg(ctx, AggregationOperator.SUM, by=("_ns_",))
        keys, vals = self.oracle_values(ctx)
        b = res.batches[0]
        for i, gk in enumerate(b.keys):
            members = [j for j, t in enumerate(keys)
                       if t["_ns_"] == gk["_ns_"]]
            expect = np.nansum(vals[members], axis=0)
            np.testing.assert_allclose(b.np_values()[i], expect, rtol=1e-9)

    def test_avg_and_count(self, ctx):
        res_a = self.run_agg(ctx, AggregationOperator.AVG)
        res_c = self.run_agg(ctx, AggregationOperator.COUNT)
        keys, vals = self.oracle_values(ctx)
        np.testing.assert_allclose(res_a.batches[0].np_values()[0],
                                   np.nanmean(vals, axis=0), rtol=1e-9)
        np.testing.assert_allclose(res_c.batches[0].np_values()[0],
                                   np.sum(np.isfinite(vals), axis=0).astype(float))

    def test_min_max_stddev(self, ctx):
        keys, vals = self.oracle_values(ctx)
        for op, fn in ((AggregationOperator.MIN, np.nanmin),
                       (AggregationOperator.MAX, np.nanmax),
                       (AggregationOperator.STDDEV,
                        lambda v, axis: np.nanstd(v, axis=axis))):
            res = self.run_agg(ctx, op)
            np.testing.assert_allclose(res.batches[0].np_values()[0],
                                       fn(vals, axis=0), rtol=1e-8)

    def test_topk(self, ctx):
        res = self.run_agg(ctx, AggregationOperator.TOPK, params=(3,))
        keys, vals = self.oracle_values(ctx)
        b = res.batches[0]
        # at each step, union of reported finite values == top-3 of oracle
        got = b.np_values()
        for t in range(got.shape[1]):
            col = got[:, t]
            top_got = np.sort(col[np.isfinite(col)])
            expect = np.sort(vals[:, t])[-3:]
            np.testing.assert_allclose(top_got, expect, rtol=1e-9)
        # result series carry original labels
        assert all("instance" in k for k in b.keys)

    def test_quantile(self, ctx):
        res = self.run_agg(ctx, AggregationOperator.QUANTILE, params=(0.5,))
        keys, vals = self.oracle_values(ctx)
        np.testing.assert_allclose(res.batches[0].np_values()[0],
                                   np.nanquantile(vals, 0.5, axis=0), rtol=1e-9)

    def test_count_values(self, ctx):
        res = self.run_agg(ctx, AggregationOperator.COUNT_VALUES,
                           params=("val",), fn=RangeFunctionId.COUNT_OVER_TIME)
        b = res.batches[0]
        assert all("val" in k for k in b.keys)
        keys, _ = self.oracle_values(ctx)
        # every step's counts sum to the total series count
        total = np.nansum(b.np_values(), axis=0)
        assert (total == len(keys)).all()


class TestJoinsAndScalars:
    def periodic(self, metric, shard=0, fn=None):
        p = leaf(metric, shard)
        p.add_transformer(PeriodicSamplesMapper(
            window_ms=60_000 if fn else None, function=fn, **grid()))
        return p

    def test_binary_join_one_to_one(self, ctx):
        lhs = self.periodic("heap_usage")
        rhs = self.periodic("heap_usage")
        join = BinaryJoinExec([lhs, rhs], 1, BinaryOperator.ADD)
        res = join.execute(ctx)
        b = res.batches[0]
        assert b.num_series == 6
        single = self.periodic("heap_usage").execute(ctx).batches[0]
        np.testing.assert_allclose(
            sorted(b.np_values()[:, 0]),
            sorted(2 * single.np_values()[:len(single.keys), 0]))
        assert all("_metric_" not in k for k in b.keys)

    def test_join_on_mismatch_drops(self, ctx):
        lhs = self.periodic("heap_usage", shard=0)
        rhs = self.periodic("heap_usage", shard=1)  # different instances
        join = BinaryJoinExec([lhs, rhs], 1, BinaryOperator.ADD)
        res = join.execute(ctx)
        assert res.batches[0].num_series == 0

    def test_set_and_or_unless(self, ctx):
        lhs = self.periodic("heap_usage", shard=0)
        rhs = self.periodic("heap_usage", shard=0)
        for op, expect in ((BinaryOperator.LAND, 6), (BinaryOperator.LOR, 6),
                           (BinaryOperator.LUNLESS, 0)):
            ex = SetOperatorExec([self.periodic("heap_usage"),
                                  self.periodic("heap_usage")], 1, op)
            res = ex.execute(ctx)
            got = res.batches[0].num_series if res.batches else 0
            assert got == expect, op

    def test_scalar_operation(self, ctx):
        p = self.periodic("heap_usage")
        p.add_transformer(ScalarOperationMapper("MUL", 2.0))
        res = p.execute(ctx)
        single = self.periodic("heap_usage").execute(ctx).batches[0]
        np.testing.assert_allclose(res.batches[0].np_values()[:len(single.keys)],
                                   2 * single.np_values()[:len(single.keys)],
                                   equal_nan=True)

    def test_scalar_comparison_filters(self, ctx):
        p = self.periodic("heap_usage")
        p.add_transformer(ScalarOperationMapper("GTR", 50.0))
        res = p.execute(ctx)
        v = res.batches[0].np_values()
        fin = v[np.isfinite(v)]
        assert (fin > 50).all()

    def test_scalar_binary_exec(self, ctx):
        g = grid()
        ex = ScalarBinaryOperationExec(BinaryOperator.ADD, 1.0, 2.0,
                                      g["start_ms"], STEP, g["end_ms"])
        res = ex.execute(ctx)
        assert (np.asarray(res.batches[0].values) == 3.0).all()

    def test_time_scalar(self, ctx):
        g = grid()
        ex = TimeScalarGeneratorExec(ScalarFunctionId.TIME, g["start_ms"],
                                     STEP, g["end_ms"])
        res = ex.execute(ctx)
        v = np.asarray(res.batches[0].values)
        assert v[0] == g["start_ms"] / 1000.0

    def test_fixed_scalar(self, ctx):
        g = grid()
        ex = ScalarFixedDoubleExec(42.0, g["start_ms"], STEP, g["end_ms"])
        res = ex.execute(ctx)
        assert (np.asarray(res.batches[0].values) == 42.0).all()


class TestTransformers:
    def periodic(self, ctx, metric="heap_usage", fn=None):
        p = MultiSchemaPartitionsExec("ds", 0, [eq("_metric_", metric)],
                                      START_TS, START_TS + 2_000_000)
        p.add_transformer(PeriodicSamplesMapper(
            window_ms=60_000 if fn else None, function=fn, **grid()))
        return p

    def test_instant_function(self, ctx):
        p = self.periodic(ctx)
        p.add_transformer(InstantVectorFunctionMapper(InstantFunctionId.ABS))
        res = p.execute(ctx)
        assert (res.batches[0].np_values()[np.isfinite(res.batches[0].np_values())] >= 0).all()

    def test_histogram_quantile_via_hist_schema(self, ctx):
        p = self.periodic(ctx, metric="req_latency",
                          fn=RangeFunctionId.RATE)
        p.add_transformer(InstantVectorFunctionMapper(
            InstantFunctionId.HISTOGRAM_QUANTILE, (0.9,)))
        res = p.execute(ctx)
        b = res.batches[0]
        v = b.np_values()[:len(b.keys)]
        assert np.isfinite(v).any()
        assert (v[np.isfinite(v)] >= 0).all()

    def test_sum_over_histograms_bucketwise(self, ctx):
        """sum(rate(hist)) aggregates bucket-wise (reference:
        HistSumRowAggregator) and histogram_quantile applies on top —
        the BASELINE config-2 query shape."""
        from filodb_tpu.ops import histogram_ops
        from filodb_tpu.query.aggregators import AggPartialBatch
        from filodb_tpu.query.logical import AggregationOperator
        from filodb_tpu.query.transformers import AggregateMapReduce, AggregatePresenter
        import jax.numpy as jnp

        # oracle: per-series hist rates, summed on host, then quantile
        per = self.periodic(ctx, metric="req_latency", fn=RangeFunctionId.RATE)
        rb = per.execute(ctx).batches[0]
        S = len(rb.keys)
        h = np.asarray(rb.hist)[:S]                       # [S, T, B]
        fin = np.isfinite(h[..., -1])
        want_hist = np.where(fin[..., None], h, 0.0).sum(axis=0)
        want_hist = np.where(fin.any(axis=0)[..., None], want_hist, np.nan)
        want_q = np.asarray(histogram_ops.hist_quantile(
            jnp.asarray(rb.bucket_tops), jnp.asarray(want_hist[None]), 0.99))[0]

        p = self.periodic(ctx, metric="req_latency", fn=RangeFunctionId.RATE)
        p.add_transformer(AggregateMapReduce(AggregationOperator.SUM))
        p.add_transformer(AggregatePresenter(AggregationOperator.SUM))
        p.add_transformer(InstantVectorFunctionMapper(
            InstantFunctionId.HISTOGRAM_QUANTILE, (0.99,)))
        res = p.execute(ctx)
        b = res.batches[0]
        got = b.np_values()[0]
        assert (np.isfinite(got) == np.isfinite(want_q)).all()
        both = np.isfinite(got)
        assert both.any()
        np.testing.assert_allclose(got[both], want_q[both], rtol=1e-6)

    def test_hist_sum_reduce_pads_bucket_widths(self, ctx):
        """Cross-shard reduce of histogram sums with different bucket
        schemes: narrower cumulative matrices edge-pad to the widest."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query.aggregators import (AggPartialBatch,
                                                  MomentAggregator)
        from filodb_tpu.query.logical import AggregationOperator

        steps = StepRange(0, 60_000, 60_000)
        agg = MomentAggregator(AggregationOperator.SUM)
        wide = AggPartialBatch(
            AggregationOperator.SUM, (), [{}], steps,
            {"hist_sum": np.ones((1, 2, 4)), "count": np.ones((1, 2))},
            bucket_tops=np.array([0.1, 0.5, 1.0, np.inf]))
        narrow = AggPartialBatch(
            AggregationOperator.SUM, (), [{}], steps,
            {"hist_sum": np.full((1, 2, 2), 2.0), "count": np.ones((1, 2))},
            bucket_tops=np.array([0.1, np.inf]))
        out = agg.reduce([wide, narrow])
        assert out.state["hist_sum"].shape == (1, 2, 4)
        # narrow's top bucket (total=2) edge-pads across the widened tail
        np.testing.assert_allclose(out.state["hist_sum"][0, 0], [3, 3, 3, 3])
        np.testing.assert_allclose(out.bucket_tops, [0.1, 0.5, 1.0, np.inf])
        pres = agg.present(out)
        assert pres.hist.shape == (1, 2, 4)

    def test_min_over_histograms_rejected(self, ctx):
        from filodb_tpu.query.logical import AggregationOperator
        from filodb_tpu.query.transformers import AggregateMapReduce
        from filodb_tpu.query.model import QueryError

        p = self.periodic(ctx, metric="req_latency", fn=RangeFunctionId.RATE)
        p.add_transformer(AggregateMapReduce(AggregationOperator.MIN))
        with pytest.raises(QueryError, match="histogram"):
            p.execute(ctx)

    def test_hist_to_prom_and_bucket_quantile(self, ctx):
        p = self.periodic(ctx, metric="req_latency",
                          fn=RangeFunctionId.SUM_OVER_TIME)
        p.add_transformer(MiscellaneousFunctionMapper(
            MiscellaneousFunctionId.HIST_TO_PROM_VECTORS))
        res = p.execute(ctx)
        b = res.batches[0]
        assert all("le" in k for k in b.keys)
        # now quantile over the exploded series
        hq = HistogramQuantileMapper(0.9)
        out = hq.apply([b], ctx)
        assert out[0].num_series == 2
        assert all("le" not in k for k in out[0].keys)

    def test_label_replace_and_join(self, ctx):
        p = self.periodic(ctx)
        p.add_transformer(MiscellaneousFunctionMapper(
            MiscellaneousFunctionId.LABEL_REPLACE,
            ("dst", "prefix-$1", "instance", "(.*)")))
        res = p.execute(ctx)
        assert all(k["dst"] == f"prefix-{k['instance']}"
                   for k in res.batches[0].keys)
        p2 = self.periodic(ctx)
        p2.add_transformer(MiscellaneousFunctionMapper(
            MiscellaneousFunctionId.LABEL_JOIN, ("joined", "-", "_ns_", "host")))
        res2 = p2.execute(ctx)
        assert all(k["joined"] == f"{k['_ns_']}-{k['host']}"
                   for k in res2.batches[0].keys)

    def test_sort(self, ctx):
        p = self.periodic(ctx)
        p.add_transformer(SortFunctionMapper(SortFunctionId.SORT_DESC))
        res = p.execute(ctx)
        v = res.batches[0].np_values()
        means = np.nanmean(v, axis=1)
        assert (np.diff(means) <= 1e-12).all()

    def test_absent_on_present_and_missing(self, ctx):
        p = self.periodic(ctx)
        p.add_transformer(AbsentFunctionMapper())
        res = p.execute(ctx)
        assert np.isnan(res.batches[0].np_values()).all()
        g = grid()
        p2 = MultiSchemaPartitionsExec("ds", 0, [eq("_metric_", "nope")],
                                       START_TS, START_TS + 2_000_000)
        p2.add_transformer(PeriodicSamplesMapper(**g))
        p2.add_transformer(AbsentFunctionMapper(
            filters=(eq("_metric_", "nope"),), start_ms=g["start_ms"],
            step_ms=STEP, end_ms=g["end_ms"]))
        res2 = p2.execute(ctx)
        assert (res2.batches[0].np_values() == 1.0).all()

    def test_stitch(self, ctx):
        g = grid()
        b1 = PeriodicBatch([{"a": "1"}],
                           __import__("filodb_tpu.ops.windows",
                                      fromlist=["StepRange"]).StepRange(
                               g["start_ms"], g["end_ms"], STEP),
                           np.array([[1.0, np.nan, 3.0] +
                                     [np.nan] * 58]))
        b2 = PeriodicBatch([{"a": "1"}], b1.steps,
                           np.array([[np.nan, 2.0, np.nan] + [4.0] * 58]))
        out = StitchRvsMapper().apply([b1, b2], ctx)
        np.testing.assert_allclose(out[0].np_values()[0][:4],
                                   [1.0, 2.0, 3.0, 4.0])


class TestMetadataExec:
    def test_part_keys_and_label_values(self, ctx):
        pk = PartKeysExec("ds", 0, [eq("_metric_", "heap_usage")], 0, MAX)
        res = pk.execute(ctx)
        assert len(res.batches[0]) == 6
        lv = LabelValuesExec("ds", 0, ["_ns_"], [], 0, MAX)
        res2 = lv.execute(ctx)
        assert "App-0" in res2.batches[0]["_ns_"]
        root = LabelValuesDistConcatExec([
            LabelValuesExec("ds", 0, ["instance"], [], 0, MAX),
            LabelValuesExec("ds", 1, ["instance"], [], 0, MAX)])
        res3 = root.execute(ctx)
        assert len(res3.batches[0]["instance"]) == 12

    def test_dist_concat(self, ctx):
        children = []
        for shard in (0, 1):
            p = leaf("heap_usage", shard)
            p.add_transformer(PeriodicSamplesMapper(**grid()))
            children.append(p)
        root = DistConcatExec(children)
        res = root.execute(ctx)
        assert sum(b.num_series for b in res.batches) == 12

    def test_print_tree(self, ctx):
        p = leaf("heap_usage")
        p.add_transformer(PeriodicSamplesMapper(**grid()))
        root = DistConcatExec([p])
        tree = root.print_tree()
        assert "DistConcatExec" in tree
        assert "MultiSchemaPartitionsExec" in tree
        assert "PeriodicSamplesMapper" in tree


class TestHistMaxSchema:
    """Histogram schema with a max column: the leaf pairs the hist kernel
    with the max plane (reference: histMaxRangeFunction — None ->
    LastSampleHistMax, sum_over_time -> SumAndMaxOverTime;
    SelectRawPartitionsExec.scala:52-63)."""

    @pytest.fixture(scope="class")
    def hm_store(self):
        from tests.data import hist_max_containers
        store = TimeSeriesMemStore()
        store.setup("hm", DEFAULT_SCHEMAS, 0)
        for off, c in enumerate(hist_max_containers(n_series=2,
                                                    n_samples=60)):
            store.ingest("hm", 0, c, off)
        return store

    def _raw(self, hm_store):
        sh = hm_store.get_shard("hm", 0)
        look = sh.lookup_partitions([eq("_metric_", "lat_hmax")], 0, MAX)
        out = {}
        for pid in look.part_ids:
            p = sh.partitions[int(pid)]
            ts, (buckets, rows) = p.read_range(0, MAX, 4)
            _, mx = p.read_range(0, MAX, 3)
            out[p.tags["instance"]] = (np.asarray(ts), np.asarray(rows),
                                       np.asarray(mx))
        return out

    def test_sum_over_time_pairs_hist_and_max(self, hm_store):
        raw = self._raw(hm_store)
        start, end, w = START_TS + 300_000, START_TS + 590_000, 300_000
        leaf = MultiSchemaPartitionsExec("hm", 0, [eq("_metric_", "lat_hmax")],
                                         start - w, end)
        leaf.add_transformer(PeriodicSamplesMapper(
            start, STEP, end, window_ms=w,
            function=RangeFunctionId.SUM_OVER_TIME))
        res = leaf.execute(ExecContext(hm_store))
        (b,) = res.batches
        assert b.hist is not None
        steps = np.asarray(b.steps.timestamps())
        for i, tags in enumerate(b.keys):
            ts, rows, mx = raw[tags["instance"]]
            for j, t in enumerate(steps):
                m = (ts > t - w) & (ts <= t)
                np.testing.assert_allclose(np.asarray(b.hist)[i, j],
                                           rows[m].sum(axis=0), rtol=1e-6)
                # values plane = max_over_time of the max column
                assert np.asarray(b.values)[i, j] == mx[m].max()

    def test_instant_selector_pairs_last_hist_and_last_max(self, hm_store):
        raw = self._raw(hm_store)
        start = end = START_TS + 590_000
        leaf = MultiSchemaPartitionsExec("hm", 0, [eq("_metric_", "lat_hmax")],
                                         start - 300_000, end)
        leaf.add_transformer(PeriodicSamplesMapper(start, STEP, end))
        res = leaf.execute(ExecContext(hm_store))
        (b,) = res.batches
        for i, tags in enumerate(b.keys):
            ts, rows, mx = raw[tags["instance"]]
            sel = ts <= start
            np.testing.assert_allclose(np.asarray(b.hist)[i, 0],
                                       rows[sel][-1], rtol=1e-6)
            assert np.asarray(b.values)[i, 0] == mx[sel][-1]

    def test_histogram_max_quantile_end_to_end(self, hm_store):
        from filodb_tpu.ops import histogram_ops
        import jax.numpy as jnp
        start, end, w = START_TS + 300_000, START_TS + 590_000, 300_000
        leaf = MultiSchemaPartitionsExec("hm", 0, [eq("_metric_", "lat_hmax")],
                                         start - w, end)
        leaf.add_transformer(PeriodicSamplesMapper(
            start, STEP, end, window_ms=w,
            function=RangeFunctionId.SUM_OVER_TIME))
        leaf.add_transformer(InstantVectorFunctionMapper(
            InstantFunctionId.HISTOGRAM_MAX_QUANTILE, (0.9,)))
        res = leaf.execute(ExecContext(hm_store))
        (b,) = res.batches
        got = np.asarray(b.values)
        assert np.isfinite(got).all()
        # oracle: hist_max_quantile over the paired planes
        leaf2 = MultiSchemaPartitionsExec("hm", 0,
                                          [eq("_metric_", "lat_hmax")],
                                          start - w, end)
        leaf2.add_transformer(PeriodicSamplesMapper(
            start, STEP, end, window_ms=w,
            function=RangeFunctionId.SUM_OVER_TIME))
        (b2,) = leaf2.execute(ExecContext(hm_store)).batches
        want = np.asarray(histogram_ops.hist_max_quantile(
            jnp.asarray(b2.bucket_tops), jnp.asarray(b2.hist),
            jnp.asarray(b2.values), 0.9))
        np.testing.assert_allclose(got, want, rtol=1e-6)
