"""End-to-end grid-vs-generic differential sweep.

The device-grid serving seam (shard.scan_grid / scan_grid_grouped) is an
OPTIMIZATION: for any query it serves, the generic scan_batch + host
kernel path must produce the same answer.  The per-kernel oracle tests
(tests/test_grid.py) cover the kernels in isolation; this sweep runs
whole PromQL queries through parse -> plan -> execute twice — once
normally (grid eligible) and once with the grid seams force-disabled —
over mixed dense/gappy data, and requires identical NaN structure and
matching values.  This is the integration net that would have caught
the round-4 staged-lane NaN bug at the query level.

Reference analog: the reference compares chunked vs sliding range-
function implementations against brute force
(query/src/test/.../rangefn/AggrOverTimeFunctionsSpec.scala); here the
two implementations are the device grid and the host fallback.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.memstore.shard import TimeSeriesShard
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext

BASE = 1_700_000_000_000
STEP = 10_000
N_ROWS = 120
SEL = '{_ws_="demo",_ns_="App-0"}'

QUERIES = [
    f'rate(m_diff{SEL}[2m])',
    f'sum(rate(m_diff{SEL}[2m]))',
    f'sum by (g) (increase(m_diff{SEL}[3m]))',
    f'avg_over_time(m_diff{SEL}[2m])',
    f'min by (g) (min_over_time(m_diff{SEL}[2m]))',
    f'max(max_over_time(m_diff{SEL}[90s]))',
    f'quantile(0.5, rate(m_diff{SEL}[2m]))',
    f'stdvar by (g) (rate(m_diff{SEL}[2m]))',
    f'count(m_diff{SEL})',
    f'sum_over_time(m_diff{SEL}[2m]) / count_over_time(m_diff{SEL}[2m])',
    f'topk(2, sum by (g)(rate(m_diff{SEL}[2m])))',
    f'last_over_time(m_diff{SEL}[1m]) * 2 + 1',
]


@pytest.fixture(scope="module")
def cluster():
    num_shards = 2
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(9)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
    full_ts = BASE + np.arange(N_ROWS, dtype=np.int64) * STEP
    for i in range(16):
        tags = {"__name__": "m_diff", "instance": f"i{i}",
                "g": f"g{i % 3}", "_ws_": "demo", "_ns_": "App-0"}
        vals = np.cumsum(rng.random(N_ROWS)) + i
        if i % 2:                      # half the series are gappy
            keep = rng.random(N_ROWS) > 0.15
            keep[0] = True
            b.add_series(full_ts[keep], [vals[keep]], tags)
        else:
            b.add_series(full_ts, [vals], tags)
    for off, c in enumerate(b.containers()):
        per = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 0) \
                % num_shards
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard("prom", sh).ingest(recs, off)
    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=0)
    return ms, planner


def _run(ms, planner, query):
    start = BASE + 240_000
    end = BASE + (N_ROWS - 2) * STEP
    plan = query_range_to_logical_plan(query, start, STEP, end)
    ep = planner.materialize(plan)
    res = ep.execute(ExecContext(ms, QueryContext()))
    out = {}
    for batch in res.batches:
        if hasattr(batch, "to_series"):
            for tags, ts, vals in batch.to_series():
                key = tuple(sorted((k, v) for k, v in tags.items()))
                out[key] = (np.asarray(ts), np.asarray(vals, np.float64))
    return out


@pytest.mark.parametrize("query", QUERIES)
def test_grid_and_generic_paths_agree(cluster, query, monkeypatch):
    ms, planner = cluster
    served = _run(ms, planner, query)
    grid_hits = sum(c.hits for sh in ms.shards("prom")
                    for c in sh.device_caches.values())

    monkeypatch.setattr(TimeSeriesShard, "scan_grid",
                        lambda self, *a, **k: None)
    monkeypatch.setattr(TimeSeriesShard, "scan_grid_grouped",
                        lambda self, *a, **k: None)
    generic = _run(ms, planner, query)

    assert served.keys() == generic.keys(), query
    assert served, f"query produced no series: {query}"
    for key in served:
        ts_s, v_s = served[key]
        ts_g, v_g = generic[key]
        np.testing.assert_array_equal(ts_s, ts_g, err_msg=query)
        np.testing.assert_array_equal(
            np.isnan(v_s), np.isnan(v_g),
            err_msg=f"NaN structure diverged: {query} {key}")
        fin = ~np.isnan(v_s)
        np.testing.assert_allclose(
            v_s[fin], v_g[fin], rtol=1e-9, atol=1e-12,
            err_msg=f"{query} {key}")
    assert grid_hits >= 0    # informational; eligibility varies per query


def test_sweep_actually_exercised_the_grid(cluster):
    """The differential is vacuous if the served runs never used the
    grid; require that the sweep's queries hit it (runs after the
    parametrized tests — module-scoped fixture keeps the caches)."""
    ms, _ = cluster
    hits = sum(c.hits for sh in ms.shards("prom")
               for c in sh.device_caches.values())
    assert hits > 0, "no differential query was served from the grid"
