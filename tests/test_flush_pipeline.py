"""Pipelined flush: freeze_raw/drain_pending split, FlushScheduler time
boundaries, and ingest-during-flush visibility.

Reference semantics being proven: flushes run on a dedicated scheduler
while the ingest thread only detaches buffers (TimeSeriesShard.scala:
756-774 prepareFlushGroup, :804-846 time-boundary createFlushTasks,
TimeSeriesMemStore.scala:106-129 flush-task-parallelism); queries see
every ingested sample exactly once throughout.
"""

import threading

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.memstore.flush import FlushScheduler
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
BASE = 1_700_000_000_000
MAX = np.iinfo(np.int64).max


def _container(ts_list, vals, tags):
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 20)
    b.add_series(ts_list, [vals], tags)
    return b.containers()


def _setup():
    ms = TimeSeriesMemStore()
    ms.setup("ds", DEFAULT_SCHEMAS, 0)
    return ms, ms.get_shard("ds", 0)


class TestFreezeDrain:
    def test_pending_visible_to_reads(self):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container(
                [BASE + i * 1000 for i in range(20)],
                list(np.arange(20.0)), tags)):
            sh.ingest_container(c, off)
        part = next(iter(sh.partitions.values()))
        assert part.freeze_raw()
        # frozen but NOT yet encoded: reads must still see all 20 rows
        ts, vals = part.read_range(0, MAX)
        assert len(ts) == 20
        np.testing.assert_array_equal(vals, np.arange(20.0))
        assert part.latest_timestamp == BASE + 19_000
        # encode on a different thread; reads stay exact afterwards
        t = threading.Thread(target=part.drain_pending)
        t.start(); t.join()
        ts2, vals2 = part.read_range(0, MAX)
        np.testing.assert_array_equal(ts2, ts)
        np.testing.assert_array_equal(vals2, vals)
        assert len(part.chunks) == 1 and not part._pending

    def test_ingest_after_freeze_keeps_order(self):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container(
                [BASE + i * 1000 for i in range(5)], [1.0] * 5, tags)):
            sh.ingest_container(c, off)
        part = next(iter(sh.partitions.values()))
        part.freeze_raw()
        for off, c in enumerate(_container(
                [BASE + 5_000 + i * 1000 for i in range(5)], [2.0] * 5,
                tags), start=1):
            sh.ingest_container(c, off)
        ts, vals = part.read_range(0, MAX)
        assert len(ts) == 10
        assert list(np.diff(ts) > 0) == [True] * 9
        part.drain_pending()
        ts2, vals2 = part.read_range(0, MAX)
        np.testing.assert_array_equal(ts2, ts)
        np.testing.assert_array_equal(vals2, vals)


class TestScheduler:
    def test_time_boundaries_staggered(self):
        ms, sh = _setup()
        sched = FlushScheduler(sh, flush_interval_ms=60_000, parallelism=2)
        tags = [{"__name__": "m", "i": str(i), "_ws_": "w", "_ns_": "n"}
                for i in range(8)]
        off = 0
        # walk time across 3 intervals; boundaries should fire per group
        for minute in range(6):
            for tg in tags:
                for c in _container([BASE + minute * 30_000], [1.0], tg):
                    sh.ingest_container(c, off); off += 1
            sched.note_ingested()
        sched.close(flush_remaining=True)
        assert sched.flushes_submitted > 0
        assert sh.stats.flushes_done == sched.flushes_submitted
        assert sh.stats.rows_ingested == 6 * 8
        # all buffers drained through the pipeline: nothing pending
        for p in sh.partitions.values():
            assert not p._pending and p._buf_n == 0

    def test_checkpoint_written_with_snapshot_offset(self):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container(
                [BASE + i * 1000 for i in range(10)],
                list(range(10)), tags)):
            sh.ingest_container(c, off)
        task = sh.prepare_flush_group(
            next(iter(sh.partitions.values())).group)
        # more data lands between prepare and run: checkpoint must carry
        # the offset snapshotted at prepare time, not the newer one
        for off, c in enumerate(_container(
                [BASE + 50_000], [9.9], tags), start=50):
            sh.ingest_container(c, off)
        sh.run_flush_task(task)
        cps = ms.meta.read_checkpoints("ds", 0)
        assert set(cps.values()) == {0}

    def test_stream_mode_end_to_end(self):
        ms, sh = _setup()
        n_series, n_rows = 6, 120
        stream = []
        off = 0
        rows_per_batch = 10
        for r0 in range(0, n_rows, rows_per_batch):
            for s in range(n_series):
                tg = {"__name__": "m", "i": str(s), "_ws_": "w", "_ns_": "n"}
                ts = [BASE + (r0 + r) * 10_000 for r in range(rows_per_batch)]
                for c in _container(ts, [float(r0 + r) for r in
                                         range(rows_per_batch)], tg):
                    stream.append((off, c)); off += 1
        total = ms.ingest_stream("ds", 0, iter(stream),
                                 flush_interval_ms=300_000)
        assert total == n_series * n_rows
        # all rows served exactly once after pipelined flushes
        for s in range(n_series):
            pid = [pid for pid, p in sh.partitions.items()
                   if p.tags.get("i") == str(s)]
            assert len(pid) == 1
            ts, vals = sh.partitions[pid[0]].read_range(0, MAX)
            assert len(ts) == n_rows
            np.testing.assert_array_equal(vals, np.arange(float(n_rows)))
        assert sh.stats.flushes_done > 0


class TestFlushFailure:
    def test_failed_flush_requeues_dirty_partkeys(self):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container([BASE + 1000], [1.0], tags)):
            sh.ingest_container(c, off)
        part = next(iter(sh.partitions.values()))
        task = sh.prepare_flush_group(part.group)
        assert task.dirty  # snapshot took them out of shard state
        assert not sh._dirty_partkeys[part.group]

        class Boom(RuntimeError):
            pass

        orig = sh.store.write_part_keys
        sh.store.write_part_keys = lambda *a, **k: (_ for _ in ()).throw(
            Boom("disk full"))
        with pytest.raises(Boom):
            sh.run_flush_task(task)
        # dirty pids are back; a healthy retry persists them + checkpoints
        assert sh._dirty_partkeys[part.group] == task.dirty
        sh.store.write_part_keys = orig
        sh.flush_group(part.group)
        assert ms.meta.read_checkpoints("ds", 0)

    def test_failed_write_chunks_requeues_chunksets(self):
        """A transient chunk-write failure must not lose chunksets: the
        retry flush persists them (idempotent by chunk id)."""
        written = []

        class FlakyStore:
            def __init__(self):
                self.fail = True

            def write_chunks(self, ds, shard, chunksets, itime):
                if self.fail:
                    raise RuntimeError("transient")
                written.extend(chunksets)

            def write_part_keys(self, ds, shard, recs):
                pass

        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS as S
        ms = TimeSeriesMemStore()
        ms.setup("ds", S, 0)
        sh = ms.get_shard("ds", 0)
        store = FlakyStore()
        sh.store = store
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container(
                [BASE + i * 1000 for i in range(8)], list(range(8)), tags)):
            sh.ingest_container(c, off)
        part = next(iter(sh.partitions.values()))
        with pytest.raises(RuntimeError):
            sh.flush_group(part.group)
        assert not written
        store.fail = False
        n = sh.flush_group(part.group)
        assert n == 1 and len(written) == 1
        assert written[0].info.num_rows == 8

    def test_scheduler_close_shuts_down_after_task_failure(self):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container([BASE + 1000], [1.0], tags)):
            sh.ingest_container(c, off)
        sh.store.write_chunks = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("boom"))
        sched = FlushScheduler(sh, flush_interval_ms=60_000)
        with pytest.raises(RuntimeError):
            sched.close(flush_remaining=True)
        assert sched._exec._shutdown  # executor really shut down


class TestConcurrentIngestQuery:
    def test_reads_exact_during_concurrent_flush_and_ingest(self):
        """A reader hammering read_range during pipelined flushes must
        always see a prefix of the ingested data with no gaps/dupes."""
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        part_holder = {}
        errors = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                part = part_holder.get("p")
                if part is None:
                    continue
                ts, vals = part.read_range(0, MAX)
                if len(ts):
                    d = np.diff(ts)
                    if not (d > 0).all():
                        errors.append("non-monotonic ts")
                        return
                    if not np.array_equal(vals * 1000.0 + BASE, ts):
                        errors.append("vals/ts mismatch")
                        return

        rt = threading.Thread(target=reader)
        rt.start()
        sched = FlushScheduler(sh, flush_interval_ms=50_000, parallelism=2)
        off = 0
        try:
            for i in range(400):
                for c in _container([BASE + i * 1000], [float(i)], tags):
                    sh.ingest_container(c, off); off += 1
                if "p" not in part_holder:
                    part_holder["p"] = next(iter(sh.partitions.values()))
                sched.note_ingested()
        finally:
            sched.close(flush_remaining=True)
            stop.set()
            rt.join()
        assert not errors, errors
        ts, vals = part_holder["p"].read_range(0, MAX)
        assert len(ts) == 400


class TestSchedulerObservability:
    """ISSUE 6 satellite: per-group last-flush age + pending-queue depth
    were never observable, and drain()/close(flush_remaining=...)
    ordering under in-flight flushes had no coverage."""

    def _slow_shard(self, delay_s=0.15):
        ms, sh = _setup()
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container(
                [BASE + i * 1000 for i in range(10)],
                list(range(10)), tags)):
            sh.ingest_container(c, off)
        orig = sh.store.write_chunks
        order = []
        started = threading.Event()

        def slow_write(ds, shard, chunksets, itime):
            import time
            started.set()
            time.sleep(delay_s)
            order.append([cs.info.num_rows for cs in chunksets])
            return orig(ds, shard, chunksets, itime)

        sh.store.write_chunks = slow_write
        return ms, sh, order, started

    def test_queue_depth_and_age_visible_during_inflight(self):
        import time
        from filodb_tpu.utils.observability import REGISTRY
        ms, sh, order, started = self._slow_shard()
        sched = FlushScheduler(sh, flush_interval_ms=60_000, parallelism=1)
        sh.flush_scheduler = sched
        group = next(iter(sh.partitions.values())).group
        assert sched.queue_depth() == 0
        age0 = sched.last_flush_age_s()
        assert age0 >= 0.0
        sched.flush_now(group)
        # in-flight: depth nonzero, exported via the gauge too
        assert sched.queue_depth() == 1
        depth = REGISTRY.gauge("filodb_flush_queue_depth")
        assert depth.value(dataset="ds", shard=0) == 1
        snap = sched.snapshot()
        assert snap["pending"] == 1
        assert snap["groups"][group]["pending"] == 1
        assert snap["groups"][group]["last_flush_age_s"] is None
        sched.drain()
        assert sched.queue_depth() == 0
        snap = sched.snapshot()
        assert snap["groups"][group]["pending"] == 0
        assert snap["groups"][group]["last_flush_age_s"] is not None
        assert sched.last_flush_age_s() < 1.0
        sched.close(flush_remaining=False)
        # gauges deregistered: no dead-instance rows after close
        assert depth.value(dataset="ds", shard=0) == 0.0
        assert "filodb_flush_queue_depth" not in "".join(
            line for line in depth.expose() if 'dataset="ds"' in line)

    def test_same_group_tasks_run_in_submission_order_inflight(self):
        """Two back-to-back submits for ONE group while the first is
        still executing must run in submission order (checkpoint
        monotonicity) even with spare pool workers."""
        ms, sh, order, started = self._slow_shard(delay_s=0.1)
        sched = FlushScheduler(sh, flush_interval_ms=60_000, parallelism=2)
        group = next(iter(sh.partitions.values())).group
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        sched.flush_now(group)              # 10 rows in flight
        assert started.wait(5.0)  # task 1 collected its chunks already
        for off, c in enumerate(_container(
                [BASE + 50_000 + i * 1000 for i in range(5)],
                [1.0] * 5, tags), start=100):
            sh.ingest_container(c, off)
        sched.flush_now(group)              # 5 more rows, must run second
        assert sched.queue_depth() == 2
        sched.drain()
        assert order == [[10], [5]]
        assert sched.queue_depth() == 0
        sched.close(flush_remaining=False)

    def test_close_flush_remaining_false_drains_but_keeps_buffered(self):
        ms, sh, order, started = self._slow_shard(delay_s=0.05)
        sched = FlushScheduler(sh, flush_interval_ms=60_000)
        group = next(iter(sh.partitions.values())).group
        sched.flush_now(group)
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container([BASE + 99_000], [7.0], tags),
                                start=200):
            sh.ingest_container(c, off)
        sched.close(flush_remaining=False)
        # the in-flight task completed...
        assert order == [[10]]
        assert sched.queue_depth() == 0
        # ...but the row ingested after it stayed buffered (stop does
        # not force a flush) and is still queryable
        part = next(iter(sh.partitions.values()))
        assert part._buf_n > 0
        ts, vals = part.read_range(0, MAX)
        assert len(ts) == 11

    def test_close_flush_remaining_true_flushes_inflight_and_buffered(self):
        ms, sh, order, started = self._slow_shard(delay_s=0.05)
        sched = FlushScheduler(sh, flush_interval_ms=60_000)
        group = next(iter(sh.partitions.values())).group
        sched.flush_now(group)
        tags = {"__name__": "m", "i": "0", "_ws_": "w", "_ns_": "n"}
        for off, c in enumerate(_container([BASE + 99_000], [7.0], tags),
                                start=200):
            sh.ingest_container(c, off)
        sched.close(flush_remaining=True)
        # both the in-flight task and the late row flushed, in order
        flat = [n for batch in order for n in batch]
        assert sum(flat) == 11 and flat[0] == 10
        assert sched.queue_depth() == 0
        for p in sh.partitions.values():
            assert p._buf_n == 0
