"""MeshAggregateExec: the fused ICI-collective serving path must be
observably identical to the per-shard scatter-gather path (reference
semantics: SingleClusterPlanner.scala:223-258 reduce tree == one psum).

Runs on the 8-device virtual CPU mesh from tests/conftest.py.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, partition_hash, \
    shard_key_hash
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext, IN_PROCESS
from filodb_tpu.query.model import QueryContext

BASE = 1_700_000_000_000
NUM_SHARDS = 4
N_SERIES = 24
N_ROWS = 120
STEP = 10_000


@pytest.fixture(scope="module")
def loaded():
    ms = TimeSeriesMemStore()
    opts = DatasetOptions()
    mapper = ShardMapper(NUM_SHARDS)
    for s in range(NUM_SHARDS):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(11)
    for i in range(N_SERIES):
        tags = {"_metric_": "mm", "inst": f"i{i}", "grp": f"g{i % 3}",
                "_ws_": "w", "_ns_": "n"}
        shard = mapper.ingestion_shard(shard_key_hash(tags, opts),
                                       partition_hash(tags, opts),
                                       2) % NUM_SHARDS
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = BASE + np.arange(N_ROWS) * STEP
        vals = np.cumsum(rng.random(N_ROWS))
        b.add_series(ts.tolist(), [vals.tolist()], tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", shard).ingest_container(c, off)
    return ms, mapper


def _planner(mapper, mesh=False, dispatcher_for_shard=None):
    provider = None
    if mesh:
        engine = MeshEngine(make_mesh())
        provider = lambda: engine  # noqa: E731
    return SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                spread_default=2,
                                dispatcher_for_shard=dispatcher_for_shard,
                                mesh_engine_provider=provider)


def _run(planner, ms, promql, start, end, step=30_000):
    plan = query_range_to_logical_plan(promql, start, step, end)
    ep = planner.materialize(plan, QueryContext())
    result = ep.execute(ExecContext(ms, QueryContext()))
    out = {}
    for b in result.batches:
        for tags, ts, vals in b.to_series():
            out[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                np.asarray(vals))
    return out


QUERIES = [
    'sum(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'count(mm{_ws_="w",_ns_="n"})',
    'avg by (grp)(mm{_ws_="w",_ns_="n"})',
    'max(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'min by (grp)(mm{_ws_="w",_ns_="n"})',
    'stddev(mm{_ws_="w",_ns_="n"})',
    'sum by (grp)(increase(mm{_ws_="w",_ns_="n"}[2m]))',
]


class TestMeshPathEquivalence:
    @pytest.mark.parametrize("promql", QUERIES)
    def test_matches_per_shard_path(self, loaded, promql):
        ms, mapper = loaded
        start = BASE + 300_000
        end = BASE + 900_000
        plain = _run(_planner(mapper), ms, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms, promql, start, end)
        assert set(fused) == set(plain)
        for k in plain:
            np.testing.assert_array_equal(fused[k][0], plain[k][0])
            np.testing.assert_allclose(fused[k][1], plain[k][1],
                                       rtol=1e-9, atol=1e-9,
                                       equal_nan=True, err_msg=str(k))

    def test_plan_shape_uses_mesh_node(self, loaded):
        ms, mapper = loaded
        planner = _planner(mapper, mesh=True)
        plan = query_range_to_logical_plan(
            'sum(rate(mm{_ws_="w",_ns_="n"}[2m]))',
            BASE + 300_000, 30_000, BASE + 900_000)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshAggregateExec" in tree
        assert "MultiSchemaPartitionsExec" not in tree  # all shards local

    @pytest.mark.parametrize("promql", [
        QUERIES[0],                           # sum(rate(...))
        'count(mm{_ws_="w",_ns_="n"})',       # COUNT exports only "count"
        'stddev(mm{_ws_="w",_ns_="n"})',
        'max by (grp)(mm{_ws_="w",_ns_="n"})',
    ])
    def test_mixed_local_remote(self, loaded, promql):
        """Shards behind a non-in-process dispatcher stay per-shard
        children; their partials merge with the mesh partial — the state
        keys must line up for every operator."""
        ms, mapper = loaded

        class LoopbackDispatcher:
            """Not IN_PROCESS identity-wise, but executes locally."""

            def dispatch(self, plan, ctx):
                return plan.execute(ctx)

        lb = LoopbackDispatcher()

        def disp(shard):
            return lb if shard == 3 else IN_PROCESS

        plain = _run(_planner(mapper), ms, promql,
                     BASE + 300_000, BASE + 900_000)
        mixed_planner = _planner(mapper, mesh=True,
                                 dispatcher_for_shard=disp)
        plan = query_range_to_logical_plan(
            promql, BASE + 300_000, 30_000, BASE + 900_000)
        ep = mixed_planner.materialize(plan, QueryContext())
        tree = ep.print_tree()
        assert "MeshAggregateExec" in tree
        assert "MultiSchemaPartitionsExec" in tree  # the remote shard
        result = ep.execute(ExecContext(ms, QueryContext()))
        out = {}
        for b in result.batches:
            for tags, ts, vals in b.to_series():
                out[tuple(sorted(tags.items()))] = np.asarray(vals)
        assert set(out) == set(plain)
        for k in plain:
            np.testing.assert_allclose(out[k], plain[k][1],
                                       rtol=1e-9, equal_nan=True)

    def test_histogram_shards_fall_back_to_host_path(self, loaded):
        """The mesh program is scalar-only; shards holding histogram data
        must be served by the per-shard host path, never dropped."""
        from tests.data import histogram_containers

        ms2 = TimeSeriesMemStore()
        mapper = ShardMapper(NUM_SHARDS)
        for s in range(NUM_SHARDS):
            ms2.setup("prom", DEFAULT_SCHEMAS, s)
        # histogram series spread over 2+ shards
        for shard_num in (0, 1, 2):
            for off, c in enumerate(histogram_containers(
                    n_series=2, n_samples=40, metric="hq",
                    seed=shard_num)):
                ms2.get_shard("prom", shard_num).ingest_container(c, off)
        promql = 'sum(rate(hq{_ws_="demo",_ns_="App-0"}[2m]))'
        from tests.data import START_TS
        start, end = START_TS + 200_000, START_TS + 390_000
        plain = _run(_planner(mapper), ms2, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms2, promql, start, end)
        assert set(fused) == set(plain) and plain, "hist data dropped"
        for k in plain:
            np.testing.assert_allclose(fused[k][1], plain[k][1],
                                       rtol=1e-6, equal_nan=True)

    def test_single_local_shard_stays_per_shard(self, loaded):
        ms, mapper = loaded
        planner = _planner(mapper, mesh=True)
        planner.spread_default = 0  # one shard per shard key
        plan = query_range_to_logical_plan(
            'sum(mm{_ws_="w",_ns_="n"})', BASE + 300_000, 30_000,
            BASE + 600_000)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshAggregateExec" not in tree
