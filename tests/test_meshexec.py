"""MeshAggregateExec: the fused ICI-collective serving path must be
observably identical to the per-shard scatter-gather path (reference
semantics: SingleClusterPlanner.scala:223-258 reduce tree == one psum).

Runs on the 8-device virtual CPU mesh from tests/conftest.py.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, partition_hash, \
    shard_key_hash
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.mesh import MeshEngine, make_mesh
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext, IN_PROCESS
from filodb_tpu.query.model import QueryContext

BASE = 1_700_000_000_000
NUM_SHARDS = 4
N_SERIES = 24
N_ROWS = 120
STEP = 10_000


@pytest.fixture(scope="module")
def loaded():
    ms = TimeSeriesMemStore()
    opts = DatasetOptions()
    mapper = ShardMapper(NUM_SHARDS)
    for s in range(NUM_SHARDS):
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(11)
    for i in range(N_SERIES):
        tags = {"_metric_": "mm", "inst": f"i{i}", "grp": f"g{i % 3}",
                "_ws_": "w", "_ns_": "n"}
        shard = mapper.ingestion_shard(shard_key_hash(tags, opts),
                                       partition_hash(tags, opts),
                                       2) % NUM_SHARDS
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], opts,
                          container_size=1 << 20)
        ts = BASE + np.arange(N_ROWS) * STEP
        vals = np.cumsum(rng.random(N_ROWS))
        b.add_series(ts.tolist(), [vals.tolist()], tags)
        for off, c in enumerate(b.containers()):
            ms.get_shard("prom", shard).ingest_container(c, off)
    return ms, mapper


def _planner(mapper, mesh=False, dispatcher_for_shard=None):
    provider = None
    if mesh:
        engine = MeshEngine(make_mesh())
        provider = lambda: engine  # noqa: E731
    return SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                spread_default=2,
                                dispatcher_for_shard=dispatcher_for_shard,
                                mesh_engine_provider=provider)


def _run(planner, ms, promql, start, end, step=30_000):
    plan = query_range_to_logical_plan(promql, start, step, end)
    ep = planner.materialize(plan, QueryContext())
    result = ep.execute(ExecContext(ms, QueryContext()))
    out = {}
    for b in result.batches:
        for tags, ts, vals in b.to_series():
            out[tuple(sorted(tags.items()))] = (np.asarray(ts),
                                                np.asarray(vals))
    return out


QUERIES = [
    'sum(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'count(mm{_ws_="w",_ns_="n"})',
    'avg by (grp)(mm{_ws_="w",_ns_="n"})',
    'max(rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'min by (grp)(mm{_ws_="w",_ns_="n"})',
    'stddev(mm{_ws_="w",_ns_="n"})',
    'sum by (grp)(increase(mm{_ws_="w",_ns_="n"}[2m]))',
    'group(mm{_ws_="w",_ns_="n"})',
    'group by (grp)(mm{_ws_="w",_ns_="n"})',
]

# the non-psum RowAggregator family: k-heap merge, member pass-through
FAMILY_QUERIES = [
    'topk(2, rate(mm{_ws_="w",_ns_="n"}[2m]))',
    'topk(3, mm{_ws_="w",_ns_="n"})',
    'bottomk(2, mm{_ws_="w",_ns_="n"})',
    'topk by (grp) (2, mm{_ws_="w",_ns_="n"})',
    'count_values("v", mm{_ws_="w",_ns_="n"})',
    'count_values by (grp) ("v", mm{_ws_="w",_ns_="n"})',
]


class TestMeshPathEquivalence:
    @pytest.mark.parametrize("promql", QUERIES)
    def test_matches_per_shard_path(self, loaded, promql):
        ms, mapper = loaded
        start = BASE + 300_000
        end = BASE + 900_000
        plain = _run(_planner(mapper), ms, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms, promql, start, end)
        assert set(fused) == set(plain)
        for k in plain:
            np.testing.assert_array_equal(fused[k][0], plain[k][0])
            np.testing.assert_allclose(fused[k][1], plain[k][1],
                                       rtol=1e-9, atol=1e-9,
                                       equal_nan=True, err_msg=str(k))

    def test_plan_shape_uses_mesh_node(self, loaded):
        ms, mapper = loaded
        planner = _planner(mapper, mesh=True)
        plan = query_range_to_logical_plan(
            'sum(rate(mm{_ws_="w",_ns_="n"}[2m]))',
            BASE + 300_000, 30_000, BASE + 900_000)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        # all shards mesh-resident here => the fused ROOT (ISSUE 18)
        assert "MeshReduceExec" in tree
        assert "MultiSchemaPartitionsExec" not in tree  # all shards local

    @pytest.mark.parametrize("promql", [
        QUERIES[0],                           # sum(rate(...))
        'count(mm{_ws_="w",_ns_="n"})',       # COUNT exports only "count"
        'stddev(mm{_ws_="w",_ns_="n"})',
        'max by (grp)(mm{_ws_="w",_ns_="n"})',
        'group(mm{_ws_="w",_ns_="n"})',
        # non-psum family: mesh partial must merge with the remote
        # shard's host-mapped partial (k-heap / member union)
        'topk by (grp) (2, mm{_ws_="w",_ns_="n"})',
        'count_values("v", mm{_ws_="w",_ns_="n"})',
    ])
    def test_mixed_local_remote(self, loaded, promql):
        """Shards behind a non-in-process dispatcher stay per-shard
        children; their partials merge with the mesh partial — the state
        keys must line up for every operator."""
        ms, mapper = loaded

        class LoopbackDispatcher:
            """Not IN_PROCESS identity-wise, but executes locally."""

            def dispatch(self, plan, ctx):
                return plan.execute(ctx)

        lb = LoopbackDispatcher()

        def disp(shard):
            return lb if shard == 3 else IN_PROCESS

        plain = _run(_planner(mapper), ms, promql,
                     BASE + 300_000, BASE + 900_000)
        mixed_planner = _planner(mapper, mesh=True,
                                 dispatcher_for_shard=disp)
        plan = query_range_to_logical_plan(
            promql, BASE + 300_000, 30_000, BASE + 900_000)
        ep = mixed_planner.materialize(plan, QueryContext())
        tree = ep.print_tree()
        assert "MeshAggregateExec" in tree
        assert "MultiSchemaPartitionsExec" in tree  # the remote shard
        result = ep.execute(ExecContext(ms, QueryContext()))
        out = {}
        for b in result.batches:
            for tags, ts, vals in b.to_series():
                out[tuple(sorted(tags.items()))] = np.asarray(vals)
        assert set(out) == set(plain)
        for k in plain:
            np.testing.assert_allclose(out[k], plain[k][1],
                                       rtol=1e-9, equal_nan=True)

    @pytest.mark.parametrize("promql", FAMILY_QUERIES)
    def test_family_matches_per_shard_path(self, loaded, promql):
        """topk/bottomk/count_values mesh partials must be observably
        identical to the per-shard path (k-heap merge / exact member
        pass-through are lossless)."""
        ms, mapper = loaded
        start = BASE + 300_000
        end = BASE + 900_000
        plain = _run(_planner(mapper), ms, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms, promql, start, end)
        assert set(fused) == set(plain) and plain
        for k in plain:
            np.testing.assert_allclose(fused[k][1], plain[k][1],
                                       rtol=1e-9, atol=1e-12,
                                       equal_nan=True, err_msg=str(k))

    def test_family_plan_uses_mesh_node(self, loaded):
        ms, mapper = loaded
        planner = _planner(mapper, mesh=True)
        for promql in (FAMILY_QUERIES[0], FAMILY_QUERIES[4],
                       'quantile(0.9, mm{_ws_="w",_ns_="n"})'):
            plan = query_range_to_logical_plan(
                promql, BASE + 300_000, 30_000, BASE + 900_000)
            tree = planner.materialize(plan, QueryContext()).print_tree()
            assert "MeshReduceExec" in tree, promql

    def test_quantile_digest_close_to_exact(self, loaded):
        """The mesh quantile partial is a t-digest sketch; the per-shard
        path is exact at this cardinality.  The estimates must agree to
        sketch accuracy and carry identical shape/keys."""
        ms, mapper = loaded
        start, end = BASE + 300_000, BASE + 900_000
        for promql in ('quantile(0.9, mm{_ws_="w",_ns_="n"})',
                       'quantile by (grp) (0.5, mm{_ws_="w",_ns_="n"})'):
            plain = _run(_planner(mapper), ms, promql, start, end)
            fused = _run(_planner(mapper, mesh=True), ms, promql,
                         start, end)
            assert set(fused) == set(plain) and plain, promql
            for k in plain:
                pv, fv = plain[k][1], fused[k][1]
                assert (np.isfinite(pv) == np.isfinite(fv)).all(), k
                fin = np.isfinite(pv)
                np.testing.assert_allclose(fv[fin], pv[fin], rtol=0.08,
                                           err_msg=f"{promql} {k}")

    def test_histogram_served_in_mesh_program(self, loaded):
        """First-class histogram sum runs IN the mesh program (bucket
        lanes + psum), identical to the per-shard host path."""
        from tests.data import histogram_containers

        ms2 = TimeSeriesMemStore()
        mapper = ShardMapper(NUM_SHARDS)
        for s in range(NUM_SHARDS):
            ms2.setup("prom", DEFAULT_SCHEMAS, s)
        for shard_num in (0, 1, 2):
            for off, c in enumerate(histogram_containers(
                    n_series=2, n_samples=40, metric="hq",
                    seed=shard_num)):
                ms2.get_shard("prom", shard_num).ingest_container(c, off)
        from tests.data import START_TS
        start, end = START_TS + 200_000, START_TS + 390_000
        for promql in ('sum(rate(hq{_ws_="demo",_ns_="App-0"}[2m]))',
                       'sum(increase(hq{_ws_="demo",_ns_="App-0"}[2m]))',
                       'sum(hq{_ws_="demo",_ns_="App-0"})'):
            plain = _run(_planner(mapper), ms2, promql, start, end)
            fused = _run(_planner(mapper, mesh=True), ms2, promql,
                         start, end)
            assert set(fused) == set(plain) and plain, promql
            for k in plain:
                np.testing.assert_allclose(fused[k][1], plain[k][1],
                                           rtol=1e-6, equal_nan=True,
                                           err_msg=f"{promql} {k}")

    def test_parameterized_op_over_histogram_falls_back_with_params(self):
        """topk over a histogram metric can't run in the hist mesh
        program (SUM-only); the per-shard fallback must carry the
        aggregation params (k) instead of dropping them."""
        from tests.data import START_TS, histogram_containers

        ms2 = TimeSeriesMemStore()
        mapper = ShardMapper(NUM_SHARDS)
        for s in range(NUM_SHARDS):
            ms2.setup("prom", DEFAULT_SCHEMAS, s)
        for shard_num in (0, 1):
            for off, c in enumerate(histogram_containers(
                    n_series=2, n_samples=40, metric="hp",
                    seed=shard_num)):
                ms2.get_shard("prom", shard_num).ingest_container(c, off)
        promql = 'topk(1, sum_over_time(hp{_ws_="demo",_ns_="App-0"}[1m]))'
        start, end = START_TS + 200_000, START_TS + 390_000
        plain = _run(_planner(mapper), ms2, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms2, promql, start, end)
        assert set(fused) == set(plain)

    def test_group_present_program(self, loaded):
        """window_aggregate (present=True) must present GROUP as
        1-where-live, consistent with the partials path."""
        from filodb_tpu.core.chunk import build_batch
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query.logical import AggregationOperator as Agg

        rng = np.random.default_rng(3)
        ts = [np.arange(30, dtype=np.int64) * 10_000 + 5_000
              for _ in range(4)]
        vs = [np.cumsum(rng.random(30)) for _ in range(4)]
        batches = [build_batch(ts[:2], vs[:2]), build_batch(ts[2:], vs[2:])]
        gids = [np.array([0, 1], np.int32), np.array([0, 1], np.int32)]
        engine = MeshEngine(make_mesh())
        out = engine.window_aggregate(
            batches, gids, num_groups=2,
            srange=StepRange(100_000, 280_000, 30_000),
            window_ms=300_000, range_fn=None, agg_op=Agg.GROUP)
        assert out.shape[0] == 2
        assert np.all(out[np.isfinite(out)] == 1.0)
        assert np.isfinite(out).any()

    def test_histogram_shards_fall_back_to_host_path(self, loaded):
        """The mesh program is scalar-only; shards holding histogram data
        must be served by the per-shard host path, never dropped."""
        from tests.data import histogram_containers

        ms2 = TimeSeriesMemStore()
        mapper = ShardMapper(NUM_SHARDS)
        for s in range(NUM_SHARDS):
            ms2.setup("prom", DEFAULT_SCHEMAS, s)
        # histogram series spread over 2+ shards
        for shard_num in (0, 1, 2):
            for off, c in enumerate(histogram_containers(
                    n_series=2, n_samples=40, metric="hq",
                    seed=shard_num)):
                ms2.get_shard("prom", shard_num).ingest_container(c, off)
        promql = 'sum(rate(hq{_ws_="demo",_ns_="App-0"}[2m]))'
        from tests.data import START_TS
        start, end = START_TS + 200_000, START_TS + 390_000
        plain = _run(_planner(mapper), ms2, promql, start, end)
        fused = _run(_planner(mapper, mesh=True), ms2, promql, start, end)
        assert set(fused) == set(plain) and plain, "hist data dropped"
        for k in plain:
            np.testing.assert_allclose(fused[k][1], plain[k][1],
                                       rtol=1e-6, equal_nan=True)

    def test_single_local_shard_stays_per_shard(self, loaded):
        ms, mapper = loaded
        planner = _planner(mapper, mesh=True)
        planner.spread_default = 0  # one shard per shard key
        plan = query_range_to_logical_plan(
            'sum(mm{_ws_="w",_ns_="n"})', BASE + 300_000, 30_000,
            BASE + 600_000)
        tree = planner.materialize(plan, QueryContext()).print_tree()
        assert "MeshAggregateExec" not in tree
