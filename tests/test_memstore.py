"""Memstore layer tests: index, partition, shard, memstore.

Mirrors the reference's memstore spec patterns — TimeSeriesMemStore with
NullColumnStore fully in-process, recovery with watermarks, eviction
(reference: core/src/test/scala/filodb.core/memstore/
TimeSeriesMemStoreSpec.scala, PartKeyLuceneIndexSpec, SURVEY.md §4).
"""

import numpy as np
import pytest

from filodb_tpu.core.filters import (ColumnFilter, Equals, EqualsRegex, In,
                                     NotEquals)
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import (IngestionConfig, StoreConfig,
                                         parse_duration_ms, parse_size)
from filodb_tpu.memstore import (PartKeyIndex, TimeSeriesMemStore,
                                 TimeSeriesPartition, TimeSeriesShard)
from filodb_tpu.store import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.utils.bloom import BloomFilter

from tests.data import (START_TS, counter_containers, gauge_containers,
                        gauge_tags, histogram_containers)

MAX = np.iinfo(np.int64).max


def eq(k, v):
    return ColumnFilter(k, Equals(v))


class TestPartKeyIndex:
    def make(self, n=10):
        idx = PartKeyIndex()
        for i in range(n):
            tags = gauge_tags(i)
            idx.add_partkey(i, str(i).encode(), tags, start_time=1000 + i)
        return idx

    def test_equals_lookup(self):
        idx = self.make()
        ids = idx.part_ids_from_filters([eq("_ns_", "App-0")])
        assert list(ids) == [0, 8]

    def test_intersection(self):
        idx = self.make()
        ids = idx.part_ids_from_filters([eq("_ns_", "App-0"), eq("host", "H0")])
        assert list(ids) == [0, 8]
        ids = idx.part_ids_from_filters([eq("_ns_", "App-1"), eq("host", "H0")])
        assert list(ids) == []

    def test_regex_and_in_and_not(self):
        idx = self.make()
        ids = idx.part_ids_from_filters([ColumnFilter("_ns_", EqualsRegex("App-[01]"))])
        assert list(ids) == [0, 1, 8, 9]
        ids = idx.part_ids_from_filters([ColumnFilter("instance", In(frozenset({"2", "3"})))])
        assert list(ids) == [2, 3]
        ids = idx.part_ids_from_filters([ColumnFilter("_ns_", NotEquals("App-0"))])
        assert 0 not in ids and 8 not in ids and len(ids) == 8

    def test_time_range_overlap(self):
        idx = self.make()
        idx.update_end_time(3, 5000)
        # query starting after part 3 ended excludes it
        ids = idx.part_ids_from_filters([], start_time=6000)
        assert 3 not in ids
        ids = idx.part_ids_from_filters([], start_time=2000, end_time=MAX)
        assert 3 in ids

    def test_eviction_order(self):
        idx = self.make()
        idx.update_end_time(5, 100)
        idx.update_end_time(2, 50)
        assert idx.part_ids_ordered_by_end_time(2) == [2, 5]

    def test_label_values_and_names(self):
        idx = self.make()
        assert idx.label_values("host") == ["H0", "H1", "H2", "H3"]
        assert idx.label_values("host", [eq("_ns_", "App-1")]) == ["H1"]
        assert "instance" in idx.label_names()

    def test_remove(self):
        idx = self.make()
        idx.remove([0, 1])
        assert len(idx) == 8
        assert 0 not in idx.part_ids_from_filters([eq("_ns_", "App-0")])


class TestPartition:
    def make(self, capacity=50):
        schema = DEFAULT_SCHEMAS["gauge"]
        return TimeSeriesPartition(0, schema, b"pk", {"a": "b"}, group=0,
                                   capacity=capacity)

    def test_append_and_read(self):
        p = self.make()
        for i in range(120):
            assert p.ingest(1000 + i * 10, (float(i),))
        assert p.num_chunks == 3  # 50+50+20
        ts, vals = p.read_range(0, MAX)
        assert len(ts) == 120
        np.testing.assert_allclose(vals, np.arange(120, dtype=float))

    def test_out_of_order_dropped(self):
        p = self.make()
        p.ingest(1000, (1.0,))
        assert not p.ingest(1000, (2.0,))
        assert not p.ingest(999, (3.0,))
        assert p.out_of_order_dropped == 2
        ts, _ = p.read_range(0, MAX)
        assert len(ts) == 1

    def test_range_filter(self):
        p = self.make(capacity=10)
        for i in range(40):
            p.ingest(1000 + i * 10, (float(i),))
        ts, vals = p.read_range(1100, 1200)
        assert ts[0] == 1100 and ts[-1] == 1200
        assert len(ts) == 11

    def test_flush_chunks_drain(self):
        p = self.make(capacity=10)
        for i in range(25):
            p.ingest(1000 + i, (float(i),))
        flushed = p.make_flush_chunks()
        assert sum(c.info.num_rows for c in flushed) == 25
        assert p.make_flush_chunks() == []
        p.ingest(5000, (1.0,))
        assert sum(c.info.num_rows for c in p.make_flush_chunks()) == 1


class TestShardIngest:
    def make_shard(self, **kw):
        cfg = StoreConfig(groups_per_shard=4, max_chunks_size=32,
                          batch_row_pad=16, batch_series_pad=4)
        return TimeSeriesShard("ds", DEFAULT_SCHEMAS, 0, cfg, **kw)

    def test_ingest_containers(self):
        shard = self.make_shard()
        total = 0
        for off, c in enumerate(gauge_containers(n_series=6, n_samples=50)):
            total += shard.ingest_container(c, off)
        assert total == 300
        assert shard.num_partitions == 6
        assert shard.stats.rows_ingested == 300

    def test_lookup_and_scan(self):
        shard = self.make_shard()
        for off, c in enumerate(gauge_containers(n_series=6, n_samples=50)):
            shard.ingest_container(c, off)
        res = shard.lookup_partitions([eq("_metric_", "heap_usage")], 0, MAX)
        assert len(res.part_ids) == 6
        tags, batch = shard.scan_batch(res.part_ids, 0, MAX)
        assert len(tags) == 6
        assert batch.num_series == 8  # padded to batch_series_pad multiple
        assert batch.max_rows >= 50
        assert int(batch.row_counts[:6].sum()) == 300
        # padding rows are NaN
        assert np.all(np.isnan(batch.values[6:]))

    def test_scan_time_window(self):
        shard = self.make_shard()
        for off, c in enumerate(gauge_containers(n_series=2, n_samples=100)):
            shard.ingest_container(c, off)
        t0 = START_TS + 200_000
        t1 = START_TS + 400_000
        _, batch = shard.scan_batch([0, 1], t0, t1)
        real = batch.timestamps[batch.timestamps != np.iinfo(np.int64).max]
        assert real.min() >= t0 and real.max() <= t1

    def test_multi_schema(self):
        shard = self.make_shard()
        off = 0
        for c in gauge_containers(n_series=2, n_samples=10):
            shard.ingest_container(c, off); off += 1
        for c in counter_containers(n_series=2, n_samples=10):
            shard.ingest_container(c, off); off += 1
        for c in histogram_containers(n_series=2, n_samples=10):
            shard.ingest_container(c, off); off += 1
        assert shard.num_partitions == 6
        res = shard.lookup_partitions([eq("_metric_", "req_latency")], 0, MAX)
        tags, batch = shard.scan_batch(res.part_ids, 0, MAX)
        assert batch.hist is not None
        assert batch.hist.shape[2] == 8  # buckets

    def test_mixed_schema_scan_locks_first(self):
        # a filter matching both gauge and histogram partitions must not
        # crash: the scan locks to the first schema (reference:
        # MultiSchemaPartitionsExec.finalizePlan picks one schema)
        shard = self.make_shard()
        off = 0
        for c in gauge_containers(n_series=2, n_samples=10):
            shard.ingest_container(c, off); off += 1
        for c in histogram_containers(n_series=2, n_samples=10):
            shard.ingest_container(c, off); off += 1
        res = shard.lookup_partitions([eq("_ws_", "demo")], 0, MAX)
        assert res.first_schema_hash is not None
        tags, batch = shard.scan_batch(res.part_ids, 0, MAX)
        assert batch is not None
        assert len(tags) == len(res.part_ids)

    def test_hist_scan_empty_window(self):
        # window past the newest sample: matched histogram partitions have
        # zero rows; the scan must return an empty batch, not crash
        shard = self.make_shard()
        for off, c in enumerate(histogram_containers(n_series=2, n_samples=5)):
            shard.ingest_container(c, off)
        res = shard.lookup_partitions([eq("_metric_", "req_latency")],
                                      START_TS + 10**9, START_TS + 2 * 10**9)
        tags, batch = shard.scan_batch(res.part_ids, START_TS + 10**9,
                                       START_TS + 2 * 10**9)
        assert batch is None or int(batch.row_counts.sum()) == 0

    def test_histogram_scan_values(self):
        shard = self.make_shard()
        for off, c in enumerate(histogram_containers(n_series=1, n_samples=5)):
            shard.ingest_container(c, off)
        res = shard.lookup_partitions([eq("_metric_", "req_latency")], 0, MAX)
        _, batch = shard.scan_batch(res.part_ids, 0, MAX)
        h = batch.hist[0, :5]
        # cumulative bucket counts are non-decreasing across buckets and rows
        assert np.all(np.diff(h, axis=1) >= 0)
        assert np.all(np.diff(h, axis=0) >= 0)


class TestFlushRecovery:
    def pipeline(self):
        store = InMemoryColumnStore()
        meta = InMemoryMetaStore()
        cfg = StoreConfig(groups_per_shard=2, max_chunks_size=16)
        shard = TimeSeriesShard("ds", DEFAULT_SCHEMAS, 0, cfg,
                                column_store=store, meta_store=meta)
        return shard, store, meta

    def test_flush_writes_chunks_partkeys_checkpoint(self):
        shard, store, meta = self.pipeline()
        for off, c in enumerate(gauge_containers(n_series=4, n_samples=40)):
            shard.ingest_container(c, off)
        n = shard.flush_all(ingestion_time=123)
        assert n > 0
        pks = list(store.scan_part_keys("ds", 0))
        assert len(pks) == 4
        cps = meta.read_checkpoints("ds", 0)
        assert set(cps.keys()) == {0, 1}
        assert all(v == shard.latest_offset for v in cps.values())
        # data round-trips through the store
        pk = pks[0].partkey
        got = list(store.read_raw_partitions("ds", 0, [pk], 0, MAX))
        assert len(got) == 1
        assert sum(cs.info.num_rows for cs in got[0][1]) == 40

    def test_recovery_skips_persisted_records(self):
        store = InMemoryColumnStore()
        meta = InMemoryMetaStore()
        cfg = StoreConfig(groups_per_shard=2, max_chunks_size=16)
        ms = TimeSeriesMemStore(store, meta)
        ms.setup("ds", DEFAULT_SCHEMAS, 0, cfg)
        containers = gauge_containers(n_series=4, n_samples=30,
                                      container_size=4096)
        stream = list(enumerate(containers))
        for off, c in stream[: len(stream) // 2]:
            ms.ingest("ds", 0, c, off)
        ms.get_shard("ds", 0).flush_all()
        persisted_offset = ms.get_shard("ds", 0).latest_offset

        # "restart": new memstore over the same stores
        ms2 = TimeSeriesMemStore(store, meta)
        ms2.setup("ds", DEFAULT_SCHEMAS, 0, cfg)
        ms2.recover_index("ds", 0)
        shard2 = ms2.get_shard("ds", 0)
        assert len(shard2.index) == 4
        n = ms2.recover_stream("ds", 0, [(off, c) for off, c in stream])
        # records at offsets <= checkpoint were skipped
        assert shard2.stats.rows_skipped > 0
        total = sum(1 for off, c in stream
                    for _ in decode_container(c, DEFAULT_SCHEMAS))
        assert n < total
        # post-recovery data covers only post-checkpoint offsets
        assert shard2.latest_offset == len(stream) - 1

    def test_eviction(self):
        shard, store, meta = self.pipeline()
        for off, c in enumerate(gauge_containers(n_series=6, n_samples=10)):
            shard.ingest_container(c, off)
        shard.flush_all()
        # mark two series stopped long ago
        evicted_pks = [shard.index.partkey(0), shard.index.partkey(1)]
        shard.index.update_end_time(0, 100)
        shard.index.update_end_time(1, 200)
        assert shard.evict_partitions(2) == 2
        assert shard.num_partitions == 4
        assert shard.stats.partitions_evicted == 2
        # evicted keys are recorded in the bloom filter
        assert all(pk in shard.evicted_keys for pk in evicted_pks)

    def test_recover_then_reingest_no_duplicates(self):
        # resumed ingest after index recovery must reuse recovered part ids
        store = InMemoryColumnStore()
        meta = InMemoryMetaStore()
        cfg = StoreConfig(groups_per_shard=2, max_chunks_size=16)
        ms = TimeSeriesMemStore(store, meta)
        ms.setup("ds", DEFAULT_SCHEMAS, 0, cfg)
        for off, c in enumerate(gauge_containers(n_series=4, n_samples=10)):
            ms.ingest("ds", 0, c, off)
        ms.flush("ds", 0)

        ms2 = TimeSeriesMemStore(store, meta)
        ms2.setup("ds", DEFAULT_SCHEMAS, 0, cfg)
        assert ms2.recover_index("ds", 0) == 4
        shard2 = ms2.get_shard("ds", 0)
        # live ingest of the SAME series resumes under recovered part ids
        late = gauge_containers(n_series=4, n_samples=10,
                                start=START_TS + 10**7)
        for off, c in enumerate(late, start=100):
            ms2.ingest("ds", 0, c, off)
        assert len(shard2.index) == 4
        assert shard2.num_partitions == 4
        assert len(shard2.part_keys([eq("_metric_", "heap_usage")], 0, MAX)) == 4

    def test_purge_expired(self):
        shard, *_ = self.pipeline()
        for off, c in enumerate(gauge_containers(n_series=3, n_samples=5)):
            shard.ingest_container(c, off)
        now = START_TS + 10**9
        assert shard.purge_expired(retention_ms=1000, now_ms=now) == 3
        assert shard.num_partitions == 0

    def test_mark_stopped_series(self):
        shard, *_ = self.pipeline()
        for off, c in enumerate(gauge_containers(n_series=2, n_samples=5)):
            shard.ingest_container(c, off)
        n = shard.mark_stopped_series(now_ms=START_TS + 10**9, stale_ms=1000)
        assert n == 2
        # they become excluded from queries starting after their end
        ids = shard.index.part_ids_from_filters([], start_time=START_TS + 10**8)
        assert len(ids) == 0


class TestMemStore:
    def test_multi_shard_label_values(self):
        ms = TimeSeriesMemStore()
        cfg = StoreConfig(groups_per_shard=2)
        ms.setup("ds", DEFAULT_SCHEMAS, 0, cfg)
        ms.setup("ds", DEFAULT_SCHEMAS, 1, cfg)
        for off, c in enumerate(gauge_containers(n_series=4, n_samples=5)):
            ms.ingest("ds", 0, c, off)
        for off, c in enumerate(gauge_containers(n_series=8, n_samples=5)):
            ms.ingest("ds", 1, c, off)
        assert ms.active_shards("ds") == [0, 1]
        vals = ms.label_values("ds", "instance")
        assert vals == sorted({str(i) for i in range(8)})

    def test_setup_twice_raises(self):
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        with pytest.raises(ValueError):
            ms.setup("ds", DEFAULT_SCHEMAS, 0)


class TestStoreConfig:
    def test_parsers(self):
        assert parse_duration_ms("1 hour") == 3_600_000
        assert parse_duration_ms("5m") == 300_000
        assert parse_duration_ms("300ms") == 300
        assert parse_size("512MB") == 512 * 1024 * 1024
        assert parse_size(1024) == 1024

    def test_from_config(self):
        cfg = StoreConfig.from_config({"flush-interval": "2h",
                                       "max-chunks-size": 100,
                                       "shard-mem-size": "256MB"})
        assert cfg.flush_interval_ms == 7_200_000
        assert cfg.max_chunks_size == 100
        assert cfg.shard_mem_size == 256 * 1024 * 1024

    def test_ingestion_config_shard_power_of_two(self):
        with pytest.raises(ValueError):
            IngestionConfig(dataset="d", num_shards=6)
        ic = IngestionConfig.from_config(
            {"dataset": "timeseries", "num-shards": 8,
             "sourceconfig": {"store": {"flush-interval": "1h"}}})
        assert ic.num_shards == 8


class TestBloom:
    def test_membership(self):
        bf = BloomFilter(1000)
        keys = [f"key-{i}".encode() for i in range(500)]
        for k in keys:
            bf.add(k)
        assert all(k in bf for k in keys)
        fp = sum(1 for i in range(10_000)
                 if f"other-{i}".encode() in bf)
        assert fp < 300  # ~1% target


class TestDeferredLabelWrites:
    """add_partkey queues label/posting writes off the ingest path
    (reference: PartKeyLuceneIndex's background flush thread); lookups
    drain first, so deferral must never be observable."""

    def test_lookup_sees_adds_before_applier_runs(self):
        idx = PartKeyIndex(auto_apply=False)
        for i in range(50):
            idx.add_partkey(i, str(i).encode(), gauge_tags(i),
                            start_time=1000 + i)
        assert idx._pending_adds            # still queued
        ids = idx.part_ids_from_filters([eq("_ns_", "App-0")])
        assert list(ids) == [0, 8, 16, 24, 32, 40, 48]
        assert not idx._pending_adds        # lookup drained them

    def test_lifetime_reads_visible_immediately(self):
        idx = PartKeyIndex(auto_apply=False)
        idx.add_partkey(7, b"7", gauge_tags(7), start_time=123)
        # the ingest thread reads these right back, pre-drain
        assert idx.start_time(7) == 123
        idx.mark_active(7)
        idx.update_end_time(7, 999)
        assert idx.end_time(7) == 999
        assert idx.partkey(7) == b"7"

    def test_remove_racing_pending_add_leaves_no_ghost(self):
        idx = PartKeyIndex(auto_apply=False)
        for i in range(20):
            idx.add_partkey(i, str(i).encode(), gauge_tags(i),
                            start_time=i)
        idx.remove([0, 8])                 # labels still queued
        ids = idx.part_ids_from_filters([eq("_ns_", "App-0")])
        assert list(ids) == [16]
        vals = idx.label_values("instance")
        assert "0" not in vals and "8" not in vals

    def test_label_surfaces_drain(self):
        idx = PartKeyIndex(auto_apply=False)
        for i in range(10):
            idx.add_partkey(i, str(i).encode(), gauge_tags(i),
                            start_time=i)
        assert "instance" in idx.label_names()
        assert idx.label_values("instance") == sorted(
            str(i) for i in range(10))

    def test_background_applier_converges(self):
        import time

        idx = PartKeyIndex()               # auto_apply on
        for i in range(2000):              # past the spawn threshold
            idx.add_partkey(i, str(i).encode(), gauge_tags(i),
                            start_time=i)
        deadline = time.time() + 10
        while time.time() < deadline and idx._pending_adds:
            time.sleep(0.05)
        # whether the applier finished or the lookup drains the tail:
        ids = idx.part_ids_from_filters([eq("_ws_", "demo")])
        assert len(ids) == 2000


class TestIndexRegexCorpusSoundness:
    """The joined-corpus regex trick must fall back to per-value
    matching for patterns that can span corpus lines or capture."""

    def test_newline_spanning_pattern(self):
        from filodb_tpu.core.filters import (ColumnFilter, Equals,
                                             EqualsRegex, NotEqualsRegex)
        from filodb_tpu.memstore.index import PartKeyIndex
        idx = PartKeyIndex()
        idx.add_partkey(0, b"", {"host": "ha", "m": "x"}, 0)
        idx.add_partkey(1, b"", {"host": "hb", "m": "x"}, 0)
        r = idx.part_ids_from_filters(
            [ColumnFilter("host", EqualsRegex("h[\\s\\S]*"))], 0, 2**62)
        assert list(r) == [0, 1]
        r = idx.part_ids_from_filters(
            [ColumnFilter("m", Equals("x")),
             ColumnFilter("host", NotEqualsRegex("h[\\s\\S]*"))], 0, 2**62)
        assert list(r) == []

    def test_capture_group_pattern(self):
        from filodb_tpu.core.filters import ColumnFilter, EqualsRegex
        from filodb_tpu.memstore.index import PartKeyIndex
        idx = PartKeyIndex()
        idx.add_partkey(0, b"", {"host": "ha"}, 0)
        idx.add_partkey(1, b"", {"host": "hb"}, 0)
        r = idx.part_ids_from_filters(
            [ColumnFilter("host", EqualsRegex("h(a|z)"))], 0, 2**62)
        assert list(r) == [0]
