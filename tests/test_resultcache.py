"""Query-frontend result cache (ISSUE 12).

The load-bearing assertion is the generative equivalence sweep:
cache-on answers are BIT-equal (``tobytes`` on the per-series value
arrays, NaN masks included) to cache-off answers across seeded rounds
of ingest-between-refreshes, chunk flush boundaries, new series
materializing (including with OLD timestamps — the case warm state
cannot see and must reset on), quarantine events, and replica
transitions mid-refresh.  Plus: invalidation proofs per epoch source,
the >=10x samples-scanned reduction on a warm cache, exact byte-LRU
reconciliation, fingerprint gating, the rollup-boundary composition,
the admin/config surface, and the tier-watermark gossip satellite."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.integrity import QUARANTINE
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import (query_range_to_logical_plan,
                                      query_to_logical_plan)
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import PeriodicBatch, QueryContext
from filodb_tpu.query.resultcache import (ResultCache, ResultCachingPlanner,
                                          plan_fingerprint)

BASE = 1_700_000_000_000
DS = "prom"


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


class _Harness:
    def __init__(self, num_shards=2, segment_ms=8_000, max_bytes=None,
                 instant=True, doorkeeper=False):
        self.mapper = ShardMapper(num_shards)
        self.mapper.register_node(range(num_shards), "local")
        self.ms = TimeSeriesMemStore()
        for s in range(num_shards):
            self.mapper.update_status(s, ShardStatus.ACTIVE)
            self.ms.setup(DS, DEFAULT_SCHEMAS, s)
        self.plain = SingleClusterPlanner(DS, self.mapper, DatasetOptions())
        inner = SingleClusterPlanner(DS, self.mapper, DatasetOptions())
        # unit tests default the doorkeeper OFF so the first
        # evaluation already populates; the sweep runs it ON (the
        # production shape)
        self.cache = ResultCache(
            DS, enabled=True, doorkeeper=doorkeeper,
            max_bytes=max_bytes if max_bytes is not None else 64 << 20)
        self.cached = ResultCachingPlanner(
            DS, inner, self.ms, self.cache, segment_ms=segment_ms,
            routing_token_fn=self.mapper.routing_token, instant=instant)
        self._offset = 0

    def ingest(self, metric, series_vals, ts):
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                          container_size=1 << 20)
        for tags, vals in series_vals:
            full = dict(tags)
            full["__name__"] = metric
            b.add_series(np.asarray(ts, dtype=np.int64),
                         [np.asarray(vals, dtype=np.float64)], full)
        n = self.mapper.num_shards
        for c in b.containers():
            per = {}
            for rec in decode_container(c, DEFAULT_SCHEMAS):
                sh = self.mapper.ingestion_shard(rec.shard_hash,
                                                 rec.part_hash, 1) % n
                per.setdefault(sh, []).append(rec)
            for sh, recs in per.items():
                self.ms.get_shard(DS, sh).ingest(recs, self._offset)
            self._offset += 1

    def flush(self):
        for sh in self.ms.shards(DS):
            sh.flush_all()

    def eval_range(self, planner, promql, start, step, end):
        plan = query_range_to_logical_plan(promql, start, step, end)
        qctx = QueryContext()
        ep = planner.materialize(plan, qctx)
        return ep.execute(ExecContext(self.ms, qctx))

    def eval_instant(self, planner, promql, t):
        plan = query_to_logical_plan(promql, t)
        qctx = QueryContext()
        ep = planner.materialize(plan, qctx)
        return ep.execute(ExecContext(self.ms, qctx))


def _series_map(res):
    """{sorted-tags: (nan mask bytes, finite values bytes)} — the
    bit-equality comparison surface (series/batch order is not part of
    the API contract; values are)."""
    out = {}
    for b in res.batches:
        if not isinstance(b, PeriodicBatch):
            continue
        for tags, ts, vals in b.to_series():
            key = tuple(sorted(tags.items()))
            vals = np.asarray(vals, dtype=np.float64)
            mask = np.isnan(vals)
            prev = out.get(key)
            if prev is not None:
                # same key split across batches: merge NaN slots
                pv = np.frombuffer(prev[2], dtype=np.float64).copy()
                pv[~mask] = vals[~mask]
                vals = pv
                mask = np.isnan(vals)
            out[key] = (mask.tobytes(), vals[~mask].tobytes(),
                        vals.tobytes())
    return {k: v[:2] for k, v in out.items()}


def _assert_bit_equal(res_a, res_b, ctx=""):
    ma, mb = _series_map(res_a), _series_map(res_b)
    assert set(ma) == set(mb), \
        f"{ctx}: series sets differ: {set(ma) ^ set(mb)}"
    for k in ma:
        assert ma[k] == mb[k], f"{ctx}: series {k} differs"


@pytest.fixture(autouse=True)
def _clean_quarantine():
    QUARANTINE.clear()
    yield
    QUARANTINE.clear()


# ---------------------------------------------------------------------------
# the generative equivalence sweep
# ---------------------------------------------------------------------------

SWEEP_QUERIES = [
    "rate(m_total{_ws_=\"w\"}[5s])",
    "sum(rate(m_total{_ws_=\"w\"}[5s]))",
    "sum by (inst) (rate(m_total{_ws_=\"w\"}[5s]))",
    "avg(rate(m_total{_ws_=\"w\"}[5s]))",
    "max(increase(m_total{_ws_=\"w\"}[6s]))",
]

INSTANT_QUERIES = [
    "rate(m_total{_ws_=\"w\"}[10s])",
    "sum(rate(m_total{_ws_=\"w\"}[10s]))",
    "sum by (inst) (rate(m_total{_ws_=\"w\"}[10s]))",
]


def _instant_pairs(res, t):
    out = {}
    for b in res.batches:
        if not isinstance(b, PeriodicBatch):
            continue
        for tags, ts, vals in b.to_series():
            fin = np.flatnonzero(~np.isnan(vals) & (ts <= t))
            if len(fin):
                out[tuple(sorted(tags.items()))] = \
                    float(vals[fin[-1]]).hex()
    return out


@pytest.mark.parametrize("seed", range(4))
def test_generative_equivalence_sweep(seed):
    rng = np.random.default_rng(seed)
    h = _Harness(num_shards=2, segment_ms=8_000, doorkeeper=True)
    series = [({"inst": f"i{i}", "_ws_": "w"}, i + 1) for i in range(4)]
    counters = {f"i{i}": 0.0 for i in range(4)}

    def grow(tags_rate, ts):
        rows = []
        for tags, r in tags_rate:
            inst = tags["inst"]
            vals = []
            for _t in ts:
                counters[inst] = counters.get(inst, 0.0) \
                    + r * (1 + rng.integers(0, 3))
                vals.append(counters[inst])
            rows.append((tags, np.asarray(vals)))
        h.ingest("m_total", rows, ts)

    # 40s of history, flushed (immutable chunks to memoize)
    grow(series, BASE + np.arange(40, dtype=np.int64) * 1000)
    h.flush()

    now = BASE + 40_000
    for rnd in range(6):
        # ingest a fresh head sliver
        ts = now + np.arange(5, dtype=np.int64) * 1000
        grow(series, ts)
        now = int(ts[-1]) + 1000
        roll = rng.random()
        if roll < 0.35:
            h.flush()                      # chunk flush boundary
        if roll < 0.2:
            # a NEW series materializing with OLD timestamps — the
            # late-arrival case warm state cannot see by delta alone
            tag = {"inst": f"late{rnd}", "_ws_": "w"}
            old = now - 20_000 + np.arange(8, dtype=np.int64) * 1000
            h.ingest("m_total", [(tag, np.cumsum(
                rng.integers(1, 4, size=8)).astype(np.float64))], old)
            series.append((tag, 1))
        if 0.2 <= roll < 0.3:
            # quarantine a random flushed chunk mid-refresh
            for sh in h.ms.shards(DS):
                for part in sh.partitions.values():
                    if part.chunks:
                        info = part.chunks[0].info
                        QUARANTINE.quarantine(
                            part.partkey, info.chunk_id, dataset=DS,
                            shard=sh.shard_num,
                            start_time=info.start_time,
                            end_time=info.end_time, reason="sweep")
                        break
                break
        if 0.3 <= roll < 0.4:
            # replica transition mid-refresh (failover shape): the
            # routing token changes and cached answers must not
            # outlive the routing view they were computed under
            h.mapper.update_status(0, ShardStatus.RECOVERY)
            h.mapper.update_status(0, ShardStatus.ACTIVE)

        start, step, end = now - 30_000, 1000, now
        for q in SWEEP_QUERIES:
            cold = h.eval_range(h.plain, q, start, step, end)
            warm1 = h.eval_range(h.cached, q, start, step, end)
            _assert_bit_equal(cold, warm1, f"seed={seed} rnd={rnd} q={q}")
            warm2 = h.eval_range(h.cached, q, start, step, end)
            _assert_bit_equal(cold, warm2,
                              f"seed={seed} rnd={rnd} q={q} (2nd)")
        for q in INSTANT_QUERIES:
            cold = _instant_pairs(h.eval_instant(h.plain, q, now), now)
            warm = _instant_pairs(h.eval_instant(h.cached, q, now), now)
            assert cold == warm, f"seed={seed} rnd={rnd} q={q}"
    # the sweep must have exercised actual cache traffic
    assert h.cache.hits > 0 and h.cache.misses > 0


# ---------------------------------------------------------------------------
# invalidation proofs (one per epoch source)
# ---------------------------------------------------------------------------


def _seeded(segment_ms=8_000, seconds=40, **kw):
    h = _Harness(segment_ms=segment_ms, **kw)
    ts = BASE + np.arange(seconds, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          np.cumsum(np.ones(seconds))),
                         ({"inst": "b", "_ws_": "w"},
                          np.cumsum(np.ones(seconds)) * 3)], ts)
    h.flush()
    return h


Q = "sum(rate(m_total{_ws_=\"w\"}[5s]))"


def test_warm_range_hits_and_samples_scanned_reduction():
    h = _seeded(segment_ms=5_000, seconds=120)
    # deliberately misaligned to the segment grid (the dashboard shape):
    # the partial first/last segments recompute, everything else hits
    start, step, end = BASE + 6_000, 1000, BASE + 116_000
    cold = h.eval_range(h.cached, Q, start, step, end)
    assert cold.stats.samples_scanned > 0
    warm = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > 0
    # acceptance: >= 10x fewer samples scanned on the second evaluation
    assert warm.stats.samples_scanned * 10 <= cold.stats.samples_scanned
    # the stats=true split reports the cached-vs-recomputed counts
    assert warm.stats.resultcache_cached_samples > 0
    assert warm.stats.resultcache_recomputed_samples == \
        warm.stats.samples_scanned
    _assert_bit_equal(h.eval_range(h.plain, Q, start, step, end), warm)


def test_quarantine_epoch_invalidates():
    h = _seeded()
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    h.eval_range(h.cached, Q, start, step, end)
    warm = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > 0
    sh = h.ms.shards(DS)[0]
    part = next(p for p in sh.partitions.values() if p.chunks)
    info = part.chunks[0].info
    assert QUARANTINE.quarantine(part.partkey, info.chunk_id, dataset=DS,
                                 shard=sh.shard_num,
                                 start_time=info.start_time,
                                 end_time=info.end_time, reason="test")
    inv0 = h.cache.invalidations
    after = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.invalidations > inv0
    plain = h.eval_range(h.plain, Q, start, step, end)
    _assert_bit_equal(plain, after)
    # warning parity: both sides exclude the quarantined chunk
    assert after.stats.corrupt_chunks_excluded == \
        plain.stats.corrupt_chunks_excluded > 0
    # and the pre-quarantine cached answer differed from the excluded
    # one, proving the invalidation actually changed the bytes served
    assert _series_map(warm) != _series_map(after)


def test_replica_transition_invalidates():
    h = _seeded()
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    h.eval_range(h.cached, Q, start, step, end)
    h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > 0
    h.mapper.update_status(1, ShardStatus.RECOVERY)
    inv0 = h.cache.invalidations
    after = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.invalidations > inv0
    _assert_bit_equal(h.eval_range(h.plain, Q, start, step, end), after)


def test_new_chunk_in_old_segment_invalidates():
    h = _seeded()
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    h.eval_range(h.cached, Q, start, step, end)
    hits0 = h.cache.hits
    h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > hits0
    # a brand-new series lands with OLD timestamps inside cached
    # segments, then flushes: the chunk digest changes
    old = BASE + 10_000 + np.arange(10, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "late", "_ws_": "w"},
                          np.cumsum(np.ones(10)))], old)
    h.flush()
    after = h.eval_range(h.cached, Q, start, step, end)
    _assert_bit_equal(h.eval_range(h.plain, Q, start, step, end), after)


def test_instant_window_incremental_and_series_reset():
    h = _seeded()
    t0 = BASE + 40_000
    q = "sum(rate(m_total{_ws_=\"w\"}[20s]))"
    cold = h.eval_instant(h.cached, q, t0)
    assert cold.stats.samples_scanned > 0
    # refresh with only a head sliver of new data
    ts = t0 + np.arange(3, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          np.array([41.0, 42.0, 43.0])),
                         ({"inst": "b", "_ws_": "w"},
                          np.array([123.0, 126.0, 129.0]))], ts)
    t1 = int(ts[-1])
    warm = h.eval_instant(h.cached, q, t1)
    assert warm.stats.samples_scanned * 5 <= cold.stats.samples_scanned
    assert warm.stats.resultcache_cached_samples > 0
    # the resident window's bytes are tracked through resize(): the
    # accounted total must follow the state's growth exactly
    accounted, walked = h.cache.reconcile()
    assert accounted == walked > 1024
    assert _instant_pairs(warm, t1) == \
        _instant_pairs(h.eval_instant(h.plain, q, t1), t1)
    # a new series appearing resets the window state (pid signature)
    h.ingest("m_total", [({"inst": "c", "_ws_": "w"},
                          np.cumsum(np.ones(15)))],
             t1 - 14_000 + np.arange(15, dtype=np.int64) * 1000)
    inv0 = h.cache.invalidations
    t2 = t1 + 1000
    after = h.eval_instant(h.cached, q, t2)
    assert h.cache.invalidations > inv0
    assert _instant_pairs(after, t2) == \
        _instant_pairs(h.eval_instant(h.plain, q, t2), t2)


# ---------------------------------------------------------------------------
# fingerprint gating + LRU/byte accounting
# ---------------------------------------------------------------------------


def _fp(promql, start=BASE, step=1000, end=BASE + 60_000):
    plan = query_range_to_logical_plan(promql, start, step, end)
    return plan_fingerprint(plan, step, start)


def test_fingerprint_allowlist():
    assert _fp("rate(m[5s])") is not None
    assert _fp("sum by (inst) (rate(m[5s]))") is not None
    assert _fp("histogram_quantile(0.99, sum by (le) (rate(m[1m])))") \
        is not None
    assert _fp("sum(rate(m[5s])) * 2") is not None
    # rank-based reduces, offsets, and joins are excluded
    assert _fp("topk(3, rate(m[5s]))") is None
    assert _fp("rate(m[5s] offset 1m)") is None
    assert _fp("a / b") is None
    assert _fp("quantile(0.5, rate(m[5s]))") is None
    # step/phase are part of the key: a shifted grid never collides
    assert _fp("rate(m[5s])", step=1000) != _fp("rate(m[5s])", step=2000)
    assert _fp("rate(m[5s])", start=BASE) != \
        _fp("rate(m[5s])", start=BASE + 500)


def test_lru_byte_accounting_reconciles_and_evicts():
    h = _seeded(max_bytes=3_000, segment_ms=5_000, seconds=60)
    start, step, end = BASE + 6_000, 1000, BASE + 56_000
    for metric in ("a", "b"):
        q = f"rate(m_total{{_ws_=\"w\",inst=\"{metric}\"}}[5s])"
        h.eval_range(h.cached, q, start, step, end)
        h.eval_range(h.cached, Q, start, step, end)
    accounted, walked = h.cache.reconcile()
    assert accounted == walked
    assert accounted <= h.cache.max_bytes
    assert h.cache.evictions > 0
    h.cache.clear()
    assert h.cache.reconcile() == (0, 0)


def test_doorkeeper_admits_only_repeating_fingerprints():
    """First sight of a fingerprint passes through untouched (a stream
    of never-repeating queries must not pay the digest/store work);
    the second sighting populates, the third hits."""
    h = _seeded(doorkeeper=True)
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    r1 = h.eval_range(h.cached, Q, start, step, end)   # doorkeeper only
    assert h.cache.snapshot()["entries"] == 0
    assert h.cache.misses == 0
    r2 = h.eval_range(h.cached, Q, start, step, end)   # split + store
    assert h.cache.snapshot()["entries"] > 0
    hits0 = h.cache.hits
    r3 = h.eval_range(h.cached, Q, start, step, end)   # hits
    assert h.cache.hits > hits0
    plain = h.eval_range(h.plain, Q, start, step, end)
    for r in (r1, r2, r3):
        _assert_bit_equal(plain, r)
    # a clear() flushes entries but keeps the admission evidence
    h.cache.clear()
    h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.snapshot()["entries"] > 0


def test_disabled_cache_is_pass_through():
    h = _seeded()
    h.cache.configure(enabled=False)
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    r1 = h.eval_range(h.cached, Q, start, step, end)
    r2 = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits == 0 and h.cache.misses == 0
    _assert_bit_equal(r1, r2)
    snap = h.cache.snapshot()
    assert snap["entries"] == 0 and not snap["enabled"]


# ---------------------------------------------------------------------------
# head-segment stable prefix (ISSUE 20, PR 17 follow-up): a warm
# dashboard's OPEN head segment replays its stable prefix and
# recomputes only the mutable sliver
# ---------------------------------------------------------------------------


def _seeded_open_head(segment_ms=8_000, flushed=35, buffered=8):
    """Flushed history + an UNFLUSHED (write-buffer) tail starting
    INSIDE the head segment (seg [32s, 40s), floor at 35s): the head
    segment stays open with a non-empty stable prefix below the
    mutable floor."""
    h = _Harness(segment_ms=segment_ms)
    ts = BASE + np.arange(flushed, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          np.cumsum(np.ones(flushed))),
                         ({"inst": "b", "_ws_": "w"},
                          np.cumsum(np.ones(flushed)) * 3)], ts)
    h.flush()
    ts2 = BASE + (flushed + np.arange(buffered, dtype=np.int64)) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          flushed + np.cumsum(np.ones(buffered))),
                         ({"inst": "b", "_ws_": "w"},
                          (flushed + np.cumsum(np.ones(buffered))) * 3)],
             ts2)
    return h


def test_open_head_segment_serves_stable_prefix():
    h = _seeded_open_head()
    # end inside the OPEN head segment [32s, 40s): the mutable floor
    # (35s) splits it into a stable prefix and the true sliver
    start, step, end = BASE + 2_000, 1000, BASE + 38_000
    cold = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.snapshot()["head_windows"], \
        "cold evaluation should memoize the head segment's stable prefix"
    hits0 = h.cache.hits
    warm = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > hits0
    _assert_bit_equal(h.eval_range(h.plain, Q, start, step, end), warm)
    # only the sliver above the stable prefix recomputes
    assert warm.stats.samples_scanned < cold.stats.samples_scanned
    from filodb_tpu.query.resultcache import _m
    assert _m()["hits"].total() > 0


def test_open_head_stays_equal_as_tail_mutates():
    # short buffered tail (samples at 35s, 36s): the sliver is hot
    h = _seeded_open_head(buffered=2)
    start, step, end = BASE + 2_000, 1000, BASE + 38_000
    h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.snapshot()["head_windows"]
    # fresh samples land in the mutable sliver between refreshes (the
    # dashboard shape); the replayed prefix + recomputed sliver must
    # serve the new rows bit-equal to the uncached answer
    ts3 = BASE + (37 + np.arange(4, dtype=np.int64)) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          37 + np.cumsum(np.ones(4))),
                         ({"inst": "b", "_ws_": "w"},
                          (37 + np.cumsum(np.ones(4))) * 3)], ts3)
    hits0 = h.cache.hits
    after = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.hits > hits0, "the stable prefix should still replay"
    _assert_bit_equal(h.eval_range(h.plain, Q, start, step, end), after)


def test_open_head_prefix_invalidates_on_old_timestamps():
    h = _seeded_open_head()
    start, step, end = BASE + 2_000, 1000, BASE + 38_000
    h.eval_range(h.cached, Q, start, step, end)
    warm = h.eval_range(h.cached, Q, start, step, end)
    assert h.cache.snapshot()["head_windows"]
    # a late series flushes chunks with OLD timestamps reaching into
    # the prefix input range: the digest changes, the stale prefix
    # must be discarded — never replayed
    old = BASE + np.arange(43, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "late", "_ws_": "w"},
                          np.cumsum(np.ones(43)) * 11)], old)
    h.flush()
    after = h.eval_range(h.cached, Q, start, step, end)
    plain = h.eval_range(h.plain, Q, start, step, end)
    _assert_bit_equal(plain, after)
    # the invalidation changed the bytes served in the prefix steps
    assert _series_map(warm) != _series_map(after)


# ---------------------------------------------------------------------------
# rollup boundary composition: the cache sits BELOW the router, so a
# moving tier boundary re-routes steps instead of serving stale entries
# ---------------------------------------------------------------------------


def test_rollup_boundary_movement_stays_equal():
    from filodb_tpu.rollup.planner import RollupRouterPlanner

    h = _Harness(segment_ms=5_000)
    n = 120
    ts = BASE + np.arange(n, dtype=np.int64) * 1000
    h.ingest("m_total", [({"inst": "a", "_ws_": "w"},
                          np.cumsum(np.ones(n)))], ts)
    h.flush()
    # a "tier" dataset on the same store: 5s-decimated copies
    for s in range(h.mapper.num_shards):
        h.ms.setup("prom_ds_5000", DEFAULT_SCHEMAS, s)
    tier_plain = SingleClusterPlanner("prom_ds_5000", h.mapper,
                                      DatasetOptions())
    tier_cache = ResultCache("prom_ds_5000", enabled=True)
    tier_cached = ResultCachingPlanner(
        "prom_ds_5000", SingleClusterPlanner("prom_ds_5000", h.mapper,
                                             DatasetOptions()),
        h.ms, tier_cache, segment_ms=5_000,
        routing_token_fn=h.mapper.routing_token)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions(),
                      container_size=1 << 20)
    dec = np.arange(0, n, 5)
    b.add_series(ts[dec], [np.cumsum(np.ones(n))[dec]],
                 {"__name__": "m_total", "inst": "a", "_ws_": "w"})
    for c in b.containers():
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = h.mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                          1) % h.mapper.num_shards
            h.ms.get_shard("prom_ds_5000", sh).ingest([rec], 0)
    boundary = [BASE + 30_000]

    def mk_router(raw, tier):
        return RollupRouterPlanner(DS, raw, {5000: tier},
                                   rolled_through_fn=lambda r: boundary[0])

    router_plain = mk_router(h.plain, tier_plain)
    router_cached = mk_router(h.cached, tier_cached)
    q = "sum(rate(m_total{_ws_=\"w\"}[10s]))"
    start, step, end = BASE + 10_000, 5000, BASE + 110_000
    for bnd in (BASE + 30_000, BASE + 60_000, BASE + 90_000):
        boundary[0] = bnd
        plan = query_range_to_logical_plan(q, start, step, end)
        res_p = mk_router(h.plain, tier_plain).materialize(
            plan, QueryContext()).execute(ExecContext(h.ms,
                                                      QueryContext()))
        res_c = router_cached.materialize(
            plan, QueryContext()).execute(ExecContext(h.ms,
                                                      QueryContext()))
        _assert_bit_equal(res_p, res_c, f"boundary={bnd}")
        res_c2 = router_cached.materialize(
            plan, QueryContext()).execute(ExecContext(h.ms,
                                                      QueryContext()))
        _assert_bit_equal(res_p, res_c2, f"boundary={bnd} (2nd)")
    assert h.cache.hits + tier_cache.hits > 0
    assert router_plain is not None


# ---------------------------------------------------------------------------
# admin + runtime config surface
# ---------------------------------------------------------------------------


def test_admin_endpoint_and_runtime_knobs():
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer

    h = _seeded()
    server = FiloHttpServer()
    server.bind_dataset(DatasetBinding(DS, h.ms, h.cached,
                                       resultcache=h.cache))
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    h.eval_range(h.cached, Q, start, step, end)
    h.eval_range(h.cached, Q, start, step, end)
    code, payload = server._resultcache({})
    assert code == 200
    snap = payload["data"]["datasets"][DS]
    assert snap["hits"] > 0 and snap["reconcile"]["exact"]
    # runtime knobs: disable + resize through /admin/config
    code, cfg = server._config({"result-cache-enabled": "false",
                                "result-cache-max-bytes": "1024"})
    assert code == 200
    assert cfg["data"]["result-cache"][DS] == {"enabled": False,
                                               "max_bytes": 1024}
    assert not h.cache.enabled and h.cache.max_bytes == 1024
    # clear flushes the entries
    code, payload = server._resultcache({"clear": "true"})
    assert payload["data"]["datasets"][DS]["entries"] == 0


def test_metrics_families_exported():
    h = _seeded()
    start, step, end = BASE + 6_000, 1000, BASE + 36_000
    h.eval_range(h.cached, Q, start, step, end)
    h.eval_range(h.cached, Q, start, step, end)
    from filodb_tpu.utils.observability import REGISTRY
    text = REGISTRY.expose_text()
    assert "filodb_resultcache_hits_total" in text
    assert "filodb_resultcache_bytes" in text


# ---------------------------------------------------------------------------
# tier-watermark gossip (ROADMAP 2b satellite)
# ---------------------------------------------------------------------------


def test_tier_watermarks_store():
    from filodb_tpu.memstore.watermarks import TierWatermarks

    tw = TierWatermarks(node="a")
    assert tw.cluster_min(DS, 60_000, ["b"]) is None   # no gossip yet
    tw.note("b", DS, {"60000": BASE + 60_000})
    tw.note("c", DS, {60_000: BASE + 30_000})
    assert tw.peer_value("b", DS, 60_000) == BASE + 60_000
    assert tw.cluster_min(DS, 60_000, ["b", "c"]) == BASE + 30_000
    # monotone: a stale poll never drags the boundary back
    tw.note("b", DS, {60_000: BASE})
    assert tw.peer_value("b", DS, 60_000) == BASE + 60_000
    # a dead owner's frozen boundary is dropped
    tw.forget("c")
    assert tw.cluster_min(DS, 60_000, ["b", "c"]) is None
    assert tw.cluster_min(DS, 60_000, ["b"]) == BASE + 60_000
    assert tw.snapshot()["b/prom"] == {"60000": BASE + 60_000}


def test_health_payload_carries_rollup_watermarks_and_poller_ingests():
    from filodb_tpu.coordinator.cluster import (FailureDetector,
                                                ShardManager, StatusPoller)
    from filodb_tpu.http.server import FiloHttpServer
    from filodb_tpu.memstore.watermarks import TierWatermarks

    class _FakeRollup:
        def rolled_snapshot(self):
            return {DS: {"60000": BASE + 42_000}}

        def admin_state(self):
            return {}

    server = FiloHttpServer(node_name="b")
    server.rollup = _FakeRollup()
    code, body = server._health()
    assert body["rollup"] == {DS: {"60000": BASE + 42_000}}

    manager = ShardManager()
    tw = TierWatermarks(node="a")
    poller = StatusPoller(manager, FailureDetector(manager),
                          peers={"b": "http://unused"}, local_node="a",
                          tier_watermarks=tw)
    poller._fetch_health = lambda ep: dict(body)
    poller.poll_once()
    assert tw.peer_value("b", DS, 60_000) == BASE + 42_000
