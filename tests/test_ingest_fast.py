"""Columnar C++ ingest fast path vs the per-record Python path.

The fast path (native cd_decode + shard._ingest_container_fast) must be
observably identical to the per-record path: same partitions, same data,
same stats, same watermark-skip and out-of-order behavior (reference
semantics: TimeSeriesShard.scala:488-522 IngestConsumer).
"""

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.native import ingestfast

pytestmark = pytest.mark.skipif(
    not ingestfast.available(), reason="native lib unavailable")

BASE = 1_700_000_000_000


def _containers(n_series=7, n_rows=50, shuffle_rows=False, seed=0,
                schema="gauge", container_size=4096):
    rng = np.random.default_rng(seed)
    b = RecordBuilder(DEFAULT_SCHEMAS[schema], container_size=container_size)
    rows = []
    for s in range(n_series):
        tags = {"__name__": "m", "inst": f"i{s}", "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.cumsum(rng.integers(1_000, 9_000, n_rows))
        vals = rng.random(n_rows) * 100
        for t, v in zip(ts, vals):
            rows.append((int(t), float(v), tags))
    if shuffle_rows:
        rng.shuffle(rows)
    for t, v, tags in rows:
        b.add(t, [v], tags)
    return b.containers()


def _snapshot(shard):
    out = {}
    for pk, pid in shard.part_set.items():
        part = shard.partitions.get(pid)
        if part is None:
            out[pk] = None
            continue
        ts, vals = part.read_range(0, np.iinfo(np.int64).max)
        out[pk] = (ts.tolist(), np.round(vals, 12).tolist(),
                   part.out_of_order_dropped, part.group)
    return out


def _ingest(containers, fast: bool):
    ms = TimeSeriesMemStore()
    ms.setup("ds", DEFAULT_SCHEMAS, 0)
    sh = ms.get_shard("ds", 0)
    for off, c in enumerate(containers):
        if fast:
            got = sh._ingest_container_fast(c, off)
            assert got is not None, "fast path unexpectedly declined"
        else:
            sh.ingest(decode_container(c, sh.schemas), off)
    return ms, sh


@pytest.mark.parametrize("shuffle", [False, True])
def test_fast_matches_slow(shuffle):
    containers = _containers(shuffle_rows=shuffle)
    _, fast = _ingest(containers, True)
    _, slow = _ingest(containers, False)
    assert fast.stats.rows_ingested == slow.stats.rows_ingested
    assert fast.stats.out_of_order_dropped == slow.stats.out_of_order_dropped
    assert fast.num_partitions == slow.num_partitions
    assert _snapshot(fast) == _snapshot(slow)


def test_fast_watermark_skip_matches():
    containers = _containers(n_series=3, n_rows=30)
    results = []
    for fast in (True, False):
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("ds", 0)
        for g in range(sh.num_groups):
            sh.group_watermarks[g] = 0 if g % 2 == 0 else 10**9
        for off, c in enumerate(containers, start=1):
            if fast:
                assert sh._ingest_container_fast(c, off) is not None
            else:
                sh.ingest(decode_container(c, sh.schemas), off)
        results.append((sh.stats.rows_ingested, sh.stats.rows_skipped,
                        _snapshot(sh)))
    assert results[0] == results[1]


def _hist_snapshot(shard):
    out = {}
    for pk, pid in shard.part_set.items():
        part = shard.partitions.get(pid)
        ts, (buckets, rows) = part.read_range(0, np.iinfo(np.int64).max, 3)
        out[pk] = (ts.tolist(), rows.tolist(),
                   buckets.bucket_tops().tolist() if buckets else None,
                   part.out_of_order_dropped)
    return out


def test_fast_histogram_matches_slow():
    """Histogram containers take the fast path (VERDICT r2 weak #3) and
    must be observably identical to the per-record blob-decode path."""
    from tests.data import histogram_containers
    containers = histogram_containers(n_series=3, n_samples=40)
    snaps = []
    for fast in (True, False):
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("ds", 0)
        for off, c in enumerate(containers):
            if fast:
                got = sh._ingest_container_fast(c, off)
                assert got is not None, "hist fast path declined"
            else:
                sh.ingest(decode_container(c, sh.schemas), off)
        snaps.append((sh.stats.rows_ingested, _hist_snapshot(sh)))
    assert snaps[0] == snaps[1]


def test_fast_histogram_scheme_switch_matches():
    """A bucket-scheme widening mid-stream must freeze buffers exactly
    like the per-record path (BucketSchemaMismatch semantics)."""
    from filodb_tpu.codecs import histcodec
    from filodb_tpu.core.histogram import GeometricBuckets
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"],
                      container_size=1 << 20)
    tags = {"__name__": "lat", "_ws_": "w", "_ns_": "n"}
    for i in range(30):
        nb = 8 if i < 15 else 12             # widen mid-stream
        buckets = GeometricBuckets(2.0, 2.0, nb)
        cum = np.arange(1, nb + 1, dtype=np.int64) * (i + 1)
        blob = histcodec.encode_hist_value(buckets, cum)
        b.add(BASE + i * 1000, (float(cum[-1]), float(cum[-1]), blob), tags)
    containers = b.containers()
    # a separate, UNIFORM container holding a third scheme with only
    # out-of-order rows: the block path must drop every row without
    # freezing buffers or moving the scheme (matching per-record
    # ingest, which drops before any scheme handling)
    b2 = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"],
                       container_size=1 << 20)
    b3 = GeometricBuckets(2.0, 2.0, 16)
    for i in range(5):
        cum3 = np.arange(1, 17, dtype=np.int64) * (i + 1)
        b2.add(BASE - 10_000 + i * 1000,
               (float(cum3[-1]), float(cum3[-1]),
                histcodec.encode_hist_value(b3, cum3)), tags)
    containers += b2.containers()
    snaps = []
    for fast in (True, False):
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("ds", 0)
        for off, c in enumerate(containers):
            if fast:
                assert sh._ingest_container_fast(c, off) is not None
            else:
                sh.ingest(decode_container(c, sh.schemas), off)
        part = next(iter(sh.partitions.values()))
        snaps.append((sh.stats.rows_ingested, len(part.chunks),
                      _hist_snapshot(sh)))
    assert snaps[0] == snaps[1]


def test_fast_counter_schema_matches():
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], container_size=1 << 20)
    tags = {"__name__": "c", "_ws_": "w", "_ns_": "n"}
    for i in range(50):
        b.add(BASE + i * 1000, [float(i % 17) * 3.5], tags)
    containers = b.containers()
    _, fast = _ingest(containers, True)
    _, slow = _ingest(containers, False)
    assert _snapshot(fast) == _snapshot(slow)


def test_decode_columnar_roundtrip():
    containers = _containers(n_series=3, n_rows=10, container_size=1 << 20)
    assert len(containers) == 1
    dec = ingestfast.decode(containers[0], DEFAULT_SCHEMAS)
    assert dec is not None
    recs = list(decode_container(containers[0], DEFAULT_SCHEMAS))
    assert dec.num_records == len(recs)
    assert len(dec.partkeys) == 3
    for i, r in enumerate(recs):
        assert int(dec.ts[i]) == r.timestamp
        assert dec.cols[0][i] == r.values[0]
        assert int(dec.shard_hashes[i]) == r.shard_hash
        assert int(dec.part_hashes[i]) == r.part_hash
        assert dec.partkeys[int(dec.uniq_idx[i])] == r.partkey()


def test_decode_malformed_falls_back():
    containers = _containers(n_series=2, n_rows=4, container_size=1 << 20)
    truncated = containers[0][:-7]
    assert ingestfast.decode(truncated, DEFAULT_SCHEMAS) is None
