"""Columnar C++ ingest fast path vs the per-record Python path.

The fast path (native cd_decode + shard._ingest_container_fast) must be
observably identical to the per-record path: same partitions, same data,
same stats, same watermark-skip and out-of-order behavior (reference
semantics: TimeSeriesShard.scala:488-522 IngestConsumer).
"""

import numpy as np
import pytest

from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.native import ingestfast

pytestmark = pytest.mark.skipif(
    not ingestfast.available(), reason="native lib unavailable")

BASE = 1_700_000_000_000


def _containers(n_series=7, n_rows=50, shuffle_rows=False, seed=0,
                schema="gauge", container_size=4096):
    rng = np.random.default_rng(seed)
    b = RecordBuilder(DEFAULT_SCHEMAS[schema], container_size=container_size)
    rows = []
    for s in range(n_series):
        tags = {"__name__": "m", "inst": f"i{s}", "_ws_": "w", "_ns_": "n"}
        ts = BASE + np.cumsum(rng.integers(1_000, 9_000, n_rows))
        vals = rng.random(n_rows) * 100
        for t, v in zip(ts, vals):
            rows.append((int(t), float(v), tags))
    if shuffle_rows:
        rng.shuffle(rows)
    for t, v, tags in rows:
        b.add(t, [v], tags)
    return b.containers()


def _snapshot(shard):
    out = {}
    for pk, pid in shard.part_set.items():
        part = shard.partitions.get(pid)
        if part is None:
            out[pk] = None
            continue
        ts, vals = part.read_range(0, np.iinfo(np.int64).max)
        out[pk] = (ts.tolist(), np.round(vals, 12).tolist(),
                   part.out_of_order_dropped, part.group)
    return out


def _ingest(containers, fast: bool):
    ms = TimeSeriesMemStore()
    ms.setup("ds", DEFAULT_SCHEMAS, 0)
    sh = ms.get_shard("ds", 0)
    for off, c in enumerate(containers):
        if fast:
            got = sh._ingest_container_fast(c, off)
            assert got is not None, "fast path unexpectedly declined"
        else:
            sh.ingest(decode_container(c, sh.schemas), off)
    return ms, sh


@pytest.mark.parametrize("shuffle", [False, True])
def test_fast_matches_slow(shuffle):
    containers = _containers(shuffle_rows=shuffle)
    _, fast = _ingest(containers, True)
    _, slow = _ingest(containers, False)
    assert fast.stats.rows_ingested == slow.stats.rows_ingested
    assert fast.stats.out_of_order_dropped == slow.stats.out_of_order_dropped
    assert fast.num_partitions == slow.num_partitions
    assert _snapshot(fast) == _snapshot(slow)


def test_fast_watermark_skip_matches():
    containers = _containers(n_series=3, n_rows=30)
    results = []
    for fast in (True, False):
        ms = TimeSeriesMemStore()
        ms.setup("ds", DEFAULT_SCHEMAS, 0)
        sh = ms.get_shard("ds", 0)
        for g in range(sh.num_groups):
            sh.group_watermarks[g] = 0 if g % 2 == 0 else 10**9
        for off, c in enumerate(containers, start=1):
            if fast:
                assert sh._ingest_container_fast(c, off) is not None
            else:
                sh.ingest(decode_container(c, sh.schemas), off)
        results.append((sh.stats.rows_ingested, sh.stats.rows_skipped,
                        _snapshot(sh)))
    assert results[0] == results[1]


def test_fast_declines_histogram_schema():
    from tests.data import histogram_containers
    containers = histogram_containers()
    ms = TimeSeriesMemStore()
    ms.setup("ds", DEFAULT_SCHEMAS, 0)
    sh = ms.get_shard("ds", 0)
    assert sh._ingest_container_fast(containers[0], 0) is None
    # and the public entry still ingests via the Python path
    assert sh.ingest_container(containers[0], 0) > 0


def test_fast_counter_schema_matches():
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"], container_size=1 << 20)
    tags = {"__name__": "c", "_ws_": "w", "_ns_": "n"}
    for i in range(50):
        b.add(BASE + i * 1000, [float(i % 17) * 3.5], tags)
    containers = b.containers()
    _, fast = _ingest(containers, True)
    _, slow = _ingest(containers, False)
    assert _snapshot(fast) == _snapshot(slow)


def test_decode_columnar_roundtrip():
    containers = _containers(n_series=3, n_rows=10, container_size=1 << 20)
    assert len(containers) == 1
    dec = ingestfast.decode(containers[0], DEFAULT_SCHEMAS)
    assert dec is not None
    recs = list(decode_container(containers[0], DEFAULT_SCHEMAS))
    assert dec.num_records == len(recs)
    assert len(dec.partkeys) == 3
    for i, r in enumerate(recs):
        assert int(dec.ts[i]) == r.timestamp
        assert dec.cols[0][i] == r.values[0]
        assert int(dec.shard_hashes[i]) == r.shard_hash
        assert int(dec.part_hashes[i]) == r.part_hash
        assert dec.partkeys[int(dec.uniq_idx[i])] == r.partkey()


def test_decode_malformed_falls_back():
    containers = _containers(n_series=2, n_rows=4, container_size=1 << 20)
    truncated = containers[0][:-7]
    assert ingestfast.decode(truncated, DEFAULT_SCHEMAS) is None
