"""Advanced planners: long-time-range routing, HA failover, federation,
regex shard keys, PromQL round-trip.

Mirrors the reference's planner specs (reference: coordinator/src/test/
.../queryplanner/LongTimeRangePlannerSpec.scala,
HighAvailabilityPlannerSpec, MultiPartitionPlannerSpec,
ShardKeyRegexPlannerSpec, LogicalPlanParserSpec — plan-shape assertions
via printTree plus end-to-end result checks)."""

import numpy as np
import pytest

from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.coordinator.planners import (FailureTimeRange,
                                             HighAvailabilityPlanner,
                                             LongTimeRangePlanner,
                                             MultiPartitionPlanner,
                                             PartitionAssignment,
                                             PromQlRemoteExec,
                                             ShardKeyRegexPlanner,
                                             SinglePartitionPlanner,
                                             StaticFailureProvider,
                                             StaticPartitionLocations,
                                             copy_with_time_range,
                                             logical_plan_to_promql)
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import (parse_query,
                                      query_range_to_logical_plan)
from filodb_tpu.query import logical as lp
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext

BASE = 1_700_000_000_000
STEP = 10_000
HOUR = 3_600_000


def _mk_cluster(dataset="prom", num_shards=2, metric="m_total", n_series=4,
                t0=BASE, n_samples=400):
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup(dataset, DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(1)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(n_series):
        tags = {"__name__": metric, "instance": f"i{i}", "_ws_": "demo",
                "_ns_": "App-0"}
        ts = t0 + np.arange(n_samples) * STEP
        vals = np.cumsum(rng.random(n_samples))
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        per = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            sh = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 0) \
                % num_shards
            per.setdefault(sh, []).append(rec)
        for sh, recs in per.items():
            ms.get_shard(dataset, sh).ingest(recs, off)
    planner = SingleClusterPlanner(dataset, mapper, DatasetOptions(),
                                   spread_default=0)
    return ms, planner


def _q(query, start, end, step=STEP):
    return query_range_to_logical_plan(query, start, step, end)


class TestCopyWithTimeRange:
    def test_rewrites_nested_plans(self):
        plan = _q('sum(rate(m_total[5m]))', BASE + HOUR, BASE + 2 * HOUR)
        new = copy_with_time_range(plan, BASE, BASE + HOUR)
        s, st, e = lp.time_range(new)
        assert (s, e) == (BASE, BASE + HOUR)
        rs = lp.leaf_raw_series(new)[0]
        # raw read extends below start by the window
        assert rs.range_selector.from_ms <= BASE - 300_000
        assert rs.range_selector.to_ms == BASE + HOUR


class TestLongTimeRangePlanner:
    def _planners(self):
        ms, raw = _mk_cluster()
        ms2, ds = _mk_cluster()
        return ms, raw, ds

    def test_routes_raw_when_recent(self):
        ms, raw, ds = self._planners()
        ltr = LongTimeRangePlanner(raw, ds, lambda: BASE - HOUR)
        ep = ltr.materialize(_q('sum(rate(m_total[5m]))', BASE + 600_000,
                                BASE + 1_200_000))
        assert "StitchRvsExec" not in ep.print_tree()

    def test_routes_downsample_when_old(self):
        ms, raw, ds = self._planners()
        ltr = LongTimeRangePlanner(raw, ds,
                                   lambda: BASE + 10 * HOUR)
        ep = ltr.materialize(_q('sum(rate(m_total[5m]))', BASE,
                                BASE + 600_000))
        assert "StitchRvsExec" not in ep.print_tree()

    def test_stitches_spanning_query(self):
        ms, raw, ds = self._planners()
        boundary = BASE + 600_000
        ltr = LongTimeRangePlanner(raw, ds, lambda: boundary)
        ep = ltr.materialize(_q('sum(rate(m_total[5m]))', BASE + 300_000,
                                BASE + 1_200_000))
        tree = ep.print_tree()
        assert "StitchRvsExec" in tree
        # executes end-to-end over real data (both planners share data here)
        res = ep.execute(ExecContext(ms, QueryContext()))
        assert res.num_series >= 1

    def test_stitched_result_covers_full_range(self):
        ms, raw, ds = self._planners()
        boundary = BASE + 800_000
        ltr = LongTimeRangePlanner(raw, ds, lambda: boundary)
        start, end = BASE + 300_000, BASE + 1_500_000
        ep = ltr.materialize(_q('sum(rate(m_total[5m]))', start, end))
        res = ep.execute(ExecContext(ms, QueryContext()))
        b = res.batches[0]
        vals = np.asarray(b.np_values())[0]
        # finite rate values on both sides of the boundary
        grid = np.asarray(b.steps.timestamps())
        left = vals[(grid < boundary) & (grid >= start + 300_000)]
        right = vals[grid >= boundary + 300_000]
        assert np.isfinite(left).any() and np.isfinite(right).any()


@pytest.fixture(scope="module")
def remote_server():
    """A live FiloHttpServer acting as the 'remote replica'."""
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
    ms, planner = _mk_cluster()
    srv = FiloHttpServer()
    srv.bind_dataset(DatasetBinding("prom", ms, planner))
    port = srv.start()
    yield f"http://127.0.0.1:{port}", ms
    srv.shutdown()


class TestPromQlRemoteExec:
    def test_remote_roundtrip(self, remote_server):
        endpoint, ms = remote_server
        ep = PromQlRemoteExec(endpoint, "prom",
                              'sum(rate(m_total{_ws_="demo",_ns_="App-0"}[5m]))',
                              BASE + 600_000, STEP, BASE + 1_200_000)
        res = ep.execute(ExecContext(ms, QueryContext()))
        assert res.num_series == 1
        vals = np.asarray(res.batches[0].np_values())[0]
        assert np.isfinite(vals).sum() > 10


class TestHighAvailabilityPlanner:
    def test_no_failures_stays_local(self, remote_server):
        endpoint, _ = remote_server
        ms, local = _mk_cluster()
        ha = HighAvailabilityPlanner("prom", local,
                                     StaticFailureProvider([]), endpoint)
        ep = ha.materialize(_q('sum(rate(m_total[5m]))', BASE + 600_000,
                               BASE + 900_000))
        assert "PromQlRemoteExec" not in ep.print_tree()

    def test_failure_window_routes_remote(self, remote_server):
        endpoint, _ = remote_server
        ms, local = _mk_cluster()
        failures = StaticFailureProvider([
            FailureTimeRange(BASE + 600_000, BASE + 800_000)])
        ha = HighAvailabilityPlanner("prom", local, failures, endpoint)
        start, end = BASE + 400_000, BASE + 1_200_000
        ep = ha.materialize(_q(
            'sum(rate(m_total{_ws_="demo",_ns_="App-0"}[5m]))', start, end))
        tree = ep.print_tree()
        assert "PromQlRemoteExec" in tree
        assert "StitchRvsExec" in tree
        res = ep.execute(ExecContext(ms, QueryContext()))
        vals = np.asarray(res.batches[0].np_values())[0]
        grid = np.asarray(res.batches[0].steps.timestamps())
        # values exist inside the failure window (served remotely)
        inside = vals[(grid >= BASE + 600_000) & (grid <= BASE + 800_000)]
        assert np.isfinite(inside).any()


class TestMetadataRemoteExec:
    """Remote metadata routing (reference: MetadataRemoteExec.scala:15)."""

    def test_ha_routes_metadata_to_replica_on_failure(self, remote_server):
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.query import logical as lp

        endpoint, remote_ms = remote_server
        _ms, local = _mk_cluster()
        failures = StaticFailureProvider([
            FailureTimeRange(BASE, BASE + 2_000_000)])
        ha = HighAvailabilityPlanner("prom", local, failures, endpoint)
        # label values route remote and return the replica's values
        plan = lp.LabelValues(("instance",), (), BASE, BASE + 1_000_000)
        ep = ha.materialize(plan, QueryContext())
        assert "MetadataRemoteExec" in ep.print_tree()
        res = ep.execute(ExecContext(_ms, QueryContext()))
        vals = res.batches[0]["instance"]
        assert sorted(vals) == [f"i{i}" for i in range(4)]
        # series keys route remote too
        plan = lp.SeriesKeysByFilters(
            (ColumnFilter("_metric_", Equals("m_total")),),
            BASE, BASE + 1_000_000)
        ep = ha.materialize(plan, QueryContext())
        assert "MetadataRemoteExec" in ep.print_tree()
        res = ep.execute(ExecContext(_ms, QueryContext()))
        keys = res.batches[0]
        assert len(keys) == 4
        assert {k.get("instance") for k in keys} == {f"i{i}"
                                                     for i in range(4)}

    def test_ha_filtered_labelvalues_keeps_filters_remotely(
            self, remote_server):
        """A filtered LabelValues routed to the replica must carry its
        filters as match[] — never silently widen the answer."""
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.query import logical as lp

        endpoint, _ = remote_server
        _ms, local = _mk_cluster()
        failures = StaticFailureProvider([
            FailureTimeRange(BASE, BASE + 2_000_000)])
        ha = HighAvailabilityPlanner("prom", local, failures, endpoint)
        plan = lp.LabelValues(
            ("instance",),
            (ColumnFilter("instance", Equals("i1")),),
            BASE, BASE + 1_000_000)
        ep = ha.materialize(plan, QueryContext())
        assert "MetadataRemoteExec" in ep.print_tree()
        res = ep.execute(ExecContext(_ms, QueryContext()))
        assert res.batches[0]["instance"] == ["i1"]

    def test_ha_metadata_stays_local_without_failures(self, remote_server):
        from filodb_tpu.query import logical as lp

        endpoint, _ = remote_server
        ms, local = _mk_cluster()
        ha = HighAvailabilityPlanner("prom", local,
                                     StaticFailureProvider([]), endpoint)
        plan = lp.LabelValues(("instance",), (), BASE, BASE + 1_000_000)
        ep = ha.materialize(plan, QueryContext())
        assert "MetadataRemoteExec" not in ep.print_tree()
        res = ep.execute(ExecContext(ms, QueryContext()))
        assert sorted(res.batches[0]["instance"]) == \
            [f"i{i}" for i in range(4)]

    def test_multipartition_metadata_fans_out_and_unions(
            self, remote_server):
        from filodb_tpu.query import logical as lp

        endpoint, _remote_ms = remote_server
        # local cluster with a DIFFERENT metric so the union is visible
        ms, local = _mk_cluster(metric="local_only_total", n_series=2)
        locs = StaticPartitionLocations([
            PartitionAssignment("remote-dc", endpoint, 0, 2**62),
            PartitionAssignment("local", "", 0, 2**62)])
        mp = MultiPartitionPlanner("prom", "local", local, locs)
        plan = lp.LabelValues(("_metric_",), (), BASE, BASE + 2_000_000)
        ep = mp.materialize(plan, QueryContext())
        tree = ep.print_tree()
        assert "MetadataRemoteExec" in tree
        assert "LabelValuesDistConcatExec" in tree
        res = ep.execute(ExecContext(ms, QueryContext()))
        got = set(res.batches[0]["_metric_"])
        assert {"m_total", "local_only_total"} <= got


class TestMultiPartitionPlanner:
    def test_local_only(self):
        ms, local = _mk_cluster()
        locs = StaticPartitionLocations([
            PartitionAssignment("local", "", 0, 2**62)])
        mp = MultiPartitionPlanner("prom", "local", local, locs)
        ep = mp.materialize(_q('sum(rate(m_total[5m]))', BASE + 600_000,
                               BASE + 900_000))
        assert "PromQlRemoteExec" not in ep.print_tree()

    def test_remote_partition_split(self, remote_server):
        endpoint, _ = remote_server
        ms, local = _mk_cluster()
        mid = BASE + 600_000
        locs = StaticPartitionLocations([
            PartitionAssignment("remote-dc", endpoint, 0, mid - 1),
            PartitionAssignment("local", "", mid, 2**62)])
        mp = MultiPartitionPlanner("prom", "local", local, locs)
        start, end = BASE + 300_000, BASE + 1_200_000
        ep = mp.materialize(_q(
            'sum(rate(m_total{_ws_="demo",_ns_="App-0"}[5m]))', start, end))
        tree = ep.print_tree()
        assert "PromQlRemoteExec" in tree and "StitchRvsExec" in tree
        res = ep.execute(ExecContext(ms, QueryContext()))
        assert res.num_series == 1

    def test_no_partitions_empty(self):
        ms, local = _mk_cluster()
        mp = MultiPartitionPlanner("prom", "local", local,
                                   StaticPartitionLocations([]))
        ep = mp.materialize(_q('sum(rate(m_total[5m]))', BASE, BASE + HOUR))
        assert "EmptyResultExec" in ep.print_tree()


class TestSinglePartitionPlanner:
    def test_selects_by_metric(self):
        ms, p1 = _mk_cluster()
        ms2, p2 = _mk_cluster()
        calls = []

        class Spy:
            def __init__(self, name, inner):
                self.name, self.inner = name, inner

            def materialize(self, plan, qctx=None):
                calls.append(self.name)
                return self.inner.materialize(plan, qctx)

        def select(plan):
            for filters in lp.raw_series_filters(plan):
                for f in filters:
                    if f.column == "_metric_":
                        return "a" if f.filter.value.startswith("m_") else "b"
            return "b"

        sp = SinglePartitionPlanner({"a": Spy("a", p1), "b": Spy("b", p2)},
                                    select)
        sp.materialize(_q('sum(rate(m_total[5m]))', BASE, BASE + HOUR))
        sp.materialize(_q('sum(rate(other[5m]))', BASE, BASE + HOUR))
        assert calls == ["a", "b"]


class TestShardKeyRegexPlanner:
    def _matcher(self, regex_keys):
        # expand _ns_ pipe-alternation into concrete keys
        out = []
        for alt in regex_keys.get("_ns_", "").split("|"):
            out.append({"_ns_": alt, **{k: v for k, v in regex_keys.items()
                                        if k != "_ns_"}})
        return out

    def test_expands_and_reduces_aggregate(self):
        ms, inner = _mk_cluster()
        skr = ShardKeyRegexPlanner(inner, self._matcher)
        ep = skr.materialize(_q(
            'sum(rate(m_total{_ws_="demo",_ns_=~"App-0|App-1"}[5m]))',
            BASE + 600_000, BASE + 900_000))
        tree = ep.print_tree()
        assert "ReduceAggregateExec" in tree
        res = ep.execute(ExecContext(ms, QueryContext()))
        assert res.num_series == 1  # one summed series across expansions

    def test_non_regex_passthrough(self):
        ms, inner = _mk_cluster()
        skr = ShardKeyRegexPlanner(inner, self._matcher)
        ep = skr.materialize(_q(
            'sum(rate(m_total{_ws_="demo",_ns_="App-0"}[5m]))',
            BASE + 600_000, BASE + 900_000))
        # no EXTRA reduce added by the regex planner on top of the
        # single-cluster planner's own
        assert ep.print_tree().count("ReduceAggregateExec") == 1

    def test_concat_for_non_aggregate(self):
        ms, inner = _mk_cluster()
        skr = ShardKeyRegexPlanner(inner, self._matcher)
        ep = skr.materialize(_q(
            'rate(m_total{_ws_="demo",_ns_=~"App-0|App-1"}[5m])',
            BASE + 600_000, BASE + 900_000))
        assert "DistConcatExec" in ep.print_tree()

    def test_plain_equals_leaf_keeps_its_selector(self):
        """Regression: a join leaf that pins a shard-key column with a
        plain Equals must not be overwritten by the regex expansion."""
        ms, inner = _mk_cluster()
        skr = ShardKeyRegexPlanner(inner, self._matcher)
        plan = _q(
            'sum(rate(m_total{_ws_="demo",_ns_=~"App-0|App-1"}[5m])) '
            '+ sum(rate(m_total{_ws_="demo",_ns_="App-9"}[5m]))',
            BASE + 600_000, BASE + 900_000)
        rewritten = skr._replace_keys(plan, {"_ns_": "App-1"},
                                      {"_ns_": "App-0|App-1"})
        selectors = []
        for filters in lp.raw_series_filters(rewritten):
            for f in filters:
                if f.column == "_ns_":
                    selectors.append(f.filter.value)
        assert sorted(selectors) == ["App-1", "App-9"]

    def test_distinct_regex_leaf_not_clobbered(self):
        """A sibling leaf carrying a DIFFERENT regex on the same shard-key
        column keeps its own regex (it is not the one being expanded)."""
        ms, inner = _mk_cluster()
        skr = ShardKeyRegexPlanner(inner, self._matcher)
        plan = _q(
            'sum(rate(m_total{_ws_="demo",_ns_=~"App-0|App-1"}[5m])) '
            '+ sum(rate(m_total{_ws_="demo",_ns_=~"App-5|App-6"}[5m]))',
            BASE + 600_000, BASE + 900_000)
        rewritten = skr._replace_keys(plan, {"_ns_": "App-0"},
                                      {"_ns_": "App-0|App-1"})
        from filodb_tpu.core.filters import EqualsRegex
        kept_regex = [f.filter.pattern
                      for filters in lp.raw_series_filters(rewritten)
                      for f in filters
                      if f.column == "_ns_"
                      and isinstance(f.filter, EqualsRegex)]
        assert kept_regex == ["App-5|App-6"]


class TestLogicalPlanToPromql:
    CASES = [
        'sum(rate(http_req_total{job="api"}[5m]))',
        'rate(http_req_total{job="api"}[5m])',
        'http_req_total{job="api"}',
        'sum(foo) by (job)',
        'count(up) without (instance)',
        'avg(foo{a=~"b.*"})',
        'abs(foo)',
        'sum(rate(foo[1m])) + sum(rate(bar[1m]))',
        'foo > 1.5',
        'topk(3, foo)',
    ]

    @pytest.mark.parametrize("query", CASES)
    def test_roundtrip(self, query):
        """render(parse(q)) must parse back to the same plan shape."""
        start, end = BASE, BASE + HOUR
        plan = parse_query(query, start, STEP, end)
        rendered = logical_plan_to_promql(plan)
        plan2 = parse_query(rendered, start, STEP, end)
        assert type(plan2) is type(plan)
        assert logical_plan_to_promql(plan2) == rendered  # fixpoint


def test_dur_rendering_precision():
    from filodb_tpu.coordinator.planners import _dur
    assert _dur(300_000) == "5m"
    assert _dur(15_000) == "15s"
    assert _dur(1_500) == "1500ms"  # never truncated to 1s
    assert _dur(500) == "500ms"


class TestTimeSplit:
    def test_long_query_splits_and_stitches(self):
        ms, _ = _mk_cluster(n_samples=400)
        from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
        mapper = ShardMapper(2)
        mapper.register_node([0, 1], "local")
        for s in range(2):
            mapper.update_status(s, ShardStatus.ACTIVE)
        split_planner = SingleClusterPlanner(
            "prom", mapper, DatasetOptions(), spread_default=0,
            min_time_range_for_split_ms=600_000, split_size_ms=600_000)
        plain_planner = SingleClusterPlanner(
            "prom", mapper, DatasetOptions(), spread_default=0)
        start, end = BASE + 300_000, BASE + 2_400_000
        plan = _q('sum(rate(m_total[5m]))', start, end)
        ep = split_planner.materialize(plan)
        tree = ep.print_tree()
        assert "StitchRvsExec" in tree
        assert tree.count("ReduceAggregateExec") >= 3  # one per split
        res = ep.execute(ExecContext(ms, QueryContext()))
        ref = plain_planner.materialize(plan).execute(
            ExecContext(ms, QueryContext()))
        got = np.asarray(res.batches[0].np_values())[0]
        want = np.asarray(ref.batches[0].np_values())[0]
        # split sub-plans re-derive raw selectors WITH lookback, so the
        # stitched result matches the unsplit plan exactly
        np.testing.assert_array_equal(np.isfinite(got), np.isfinite(want))
        fin = np.isfinite(got)
        np.testing.assert_allclose(got[fin], want[fin], rtol=1e-9)

    def test_short_query_not_split(self):
        from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
        mapper = ShardMapper(2)
        mapper.register_node([0, 1], "local")
        for s in range(2):
            mapper.update_status(s, ShardStatus.ACTIVE)
        planner = SingleClusterPlanner(
            "prom", mapper, DatasetOptions(), spread_default=0,
            min_time_range_for_split_ms=3_600_000)
        ep = planner.materialize(_q('sum(rate(m_total[5m]))',
                                    BASE, BASE + 600_000))
        assert "StitchRvsExec" not in ep.print_tree()
