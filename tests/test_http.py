"""HTTP API: Prometheus-compatible routes against a live threaded server.

Mirrors the reference's HTTP route specs (reference:
http/src/test/.../PrometheusApiRouteSpec.scala — parse -> plan -> execute
-> Prometheus JSON; HealthRoute / ClusterApiRoute specs).
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import ShardManager
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.model import parse_duration_ms, parse_time_ms
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus

BASE = 1_700_000_000_000
STEP = 10_000


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(port, path, **params):
    data = urllib.parse.urlencode(params).encode()
    url = f"http://127.0.0.1:{port}{path}"
    req = urllib.request.Request(url, data=data, method="POST")
    req.add_header("Content-Type", "application/x-www-form-urlencoded")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def server():
    num_shards = 4
    mapper = ShardMapper(num_shards)
    mapper.register_node(range(num_shards), "local")
    ms = TimeSeriesMemStore()
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)
        ms.setup("prom", DEFAULT_SCHEMAS, s)
    rng = np.random.default_rng(0)
    builder = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(6):
        tags = {"__name__": "http_requests_total", "job": "api",
                "instance": f"i{i}", "_ws_": "demo", "_ns_": "App-0"}
        ts = BASE + np.arange(200) * STEP
        vals = np.cumsum(rng.random(200) * 5)
        for t, v in zip(ts, vals):
            builder.add(int(t), [float(v)], tags)
    spread = 1
    for off, c in enumerate(builder.containers()):
        per_shard = {}
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash,
                                           spread) % num_shards
            per_shard.setdefault(shard, []).append(rec)
        for shard, recs in per_shard.items():
            ms.get_shard("prom", shard).ingest(recs, off)

    mgr = ShardManager()
    mgr.setup_dataset("prom", num_shards, min_num_nodes=1)
    mgr.add_node("local")

    planner = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                   spread_default=spread)
    srv = FiloHttpServer(shard_manager=mgr)
    srv.bind_dataset(DatasetBinding("prom", ms, planner))
    port = srv.start()
    yield port
    srv.shutdown()


class TestQueryRange:
    def test_matrix_result(self, server):
        code, body = _get(server, "/promql/prom/api/v1/query_range",
                          query='sum(rate(http_requests_total{_ws_="demo",_ns_="App-0"}[2m]))',
                          start=(BASE + 600_000) / 1000,
                          end=(BASE + 1_200_000) / 1000, step="30s")
        assert code == 200
        assert body["status"] == "success"
        assert body["data"]["resultType"] == "matrix"
        result = body["data"]["result"]
        assert len(result) == 1  # sum() -> one series
        values = result[0]["values"]
        assert len(values) > 10
        ts0, v0 = values[0]
        assert float(v0) > 0  # positive rate of a counter
        # timestamps are unix seconds on the step grid
        assert abs(ts0 * 1000 - round(ts0 * 1000)) < 1e-6

    def test_raw_selector(self, server):
        code, body = _get(server, "/promql/prom/api/v1/query_range",
                          query='http_requests_total{job="api"}',
                          start=(BASE + 300_000) / 1000,
                          end=(BASE + 900_000) / 1000, step="10s")
        assert code == 200
        assert len(body["data"]["result"]) == 6
        metrics = {r["metric"]["instance"] for r in body["data"]["result"]}
        assert metrics == {f"i{i}" for i in range(6)}

    def test_post_form(self, server):
        code, body = _post(server, "/promql/prom/api/v1/query_range",
                           query='count(http_requests_total)',
                           start=(BASE + 600_000) / 1000,
                           end=(BASE + 700_000) / 1000, step="30s")
        assert code == 200
        vals = body["data"]["result"][0]["values"]
        assert all(v == "6" for _, v in vals)

    def test_post_json_numeric_params(self, server):
        """JSON bodies may carry numbers; they must behave like their
        query-string (string) equivalents."""
        url = (f"http://127.0.0.1:{server}"
               "/promql/prom/api/v1/query_range")
        req = urllib.request.Request(
            url, method="POST",
            data=json.dumps({"query": "count(http_requests_total)",
                             "start": (BASE + 600_000) / 1000,
                             "end": (BASE + 700_000) / 1000,
                             "step": 30}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            body = json.loads(resp.read())
        assert body["status"] == "success"
        vals = body["data"]["result"][0]["values"]
        assert all(v == "6" for _, v in vals)

    def test_post_json_array_is_400(self, server):
        """A JSON array body is a client error, not a 500."""
        url = (f"http://127.0.0.1:{server}"
               "/promql/prom/api/v1/query_range")
        req = urllib.request.Request(
            url, method="POST", data=json.dumps([1, 2]).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
        assert json.loads(ei.value.read())["errorType"] == "bad_data"

    def test_parse_error_is_400(self, server):
        code, body = _get(server, "/promql/prom/api/v1/query_range",
                          query='sum(rate(', start="1", end="2", step="15s")
        assert code == 400
        assert body["status"] == "error"

    def test_unknown_dataset_404(self, server):
        code, body = _get(server, "/promql/nope/api/v1/query_range",
                          query="up", start="1", end="2")
        assert code == 404


class TestInstantQuery:
    def test_vector_result(self, server):
        code, body = _get(server, "/promql/prom/api/v1/query",
                          query='http_requests_total{instance="i0"}',
                          time=(BASE + 900_000) / 1000)
        assert code == 200
        assert body["data"]["resultType"] == "vector"
        assert len(body["data"]["result"]) == 1
        t, v = body["data"]["result"][0]["value"]
        assert t == (BASE + 900_000) / 1000
        assert float(v) > 0

    def test_scalar(self, server):
        code, body = _get(server, "/promql/prom/api/v1/query",
                          query="scalar(count(http_requests_total))",
                          time=(BASE + 900_000) / 1000)
        assert code == 200
        assert body["data"]["resultType"] == "scalar"
        assert body["data"]["value"][1] == "6"


class TestMetadata:
    def test_labels(self, server):
        code, body = _get(server, "/promql/prom/api/v1/labels")
        assert code == 200
        assert "job" in body["data"] and "instance" in body["data"]

    def test_label_values(self, server):
        code, body = _get(server, "/promql/prom/api/v1/label/instance/values")
        assert code == 200
        assert body["data"] == [f"i{i}" for i in range(6)]

    def test_series(self, server):
        code, body = _get(server, "/promql/prom/api/v1/series",
                          **{"match[]": 'http_requests_total{instance=~"i[01]"}'})
        assert code == 200
        insts = sorted(s["instance"] for s in body["data"])
        assert insts == ["i0", "i1"]


class TestAdmin:
    def test_health(self, server):
        code, body = _get(server, "/__health")
        assert code == 200
        assert body["healthy"] is True
        statuses = {s["status"] for s in body["shards"]["prom"]}
        assert statuses <= {"Active", "Assigned", "Recovery"}

    def test_cluster_status(self, server):
        code, body = _get(server, "/api/v1/cluster/prom/status")
        assert code == 200
        assert len(body["data"]) == 4
        assert all(s["node"] == "local" for s in body["data"])

    def test_stop_start_shards(self, server):
        code, body = _post(server, "/api/v1/cluster/prom/stopshards",
                           shards="3")
        assert code == 200 and body["data"] == [3]
        code, body = _get(server, "/api/v1/cluster/prom/status")
        assert body["data"][3]["status"] == "Stopped"
        # startshards requires an unassigned shard: stopped keeps its node,
        # so this is a no-op returning []
        code, body = _post(server, "/api/v1/cluster/prom/startshards",
                           shards="3", node="local")
        assert code == 200 and body["data"] == []
        # missing node param on startshards is a 400, not a 500
        code, body = _post(server, "/api/v1/cluster/prom/startshards",
                           shards="3")
        assert code == 400


def test_param_parsing():
    assert parse_time_ms("1700000000") == 1_700_000_000_000
    assert parse_time_ms("1700000000.5") == 1_700_000_000_500
    assert parse_duration_ms("15s") == 15_000
    assert parse_duration_ms("1m") == 60_000
    assert parse_duration_ms("250ms") == 250
    assert parse_duration_ms("2h") == 7_200_000
    assert parse_duration_ms("30") == 30_000
