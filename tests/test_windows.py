"""Windowed range-function kernels vs the brute-force numpy oracle.

Data is irregular (jittered intervals, missing samples, NaNs, counter
resets, ragged series lengths) to exercise searchsorted bounds, padding and
correction — the conditions SURVEY.md §7 calls the hard parts."""

import numpy as np
import pytest
import jax.numpy as jnp

import oracle
from filodb_tpu.core.chunk import build_batch
from filodb_tpu.ops import windows as W

rng = np.random.default_rng(123)

START, END, STEP, WINDOW = 1_000_000, 1_360_000, 15_000, 60_000
STEPS = np.arange(START, END + 1, STEP)


def make_series(n, kind="gauge", with_nans=False, seed=0):
    r = np.random.default_rng(seed)
    ts = START - WINDOW + np.sort(r.choice(np.arange(0, END - START + 2 * WINDOW, 1000),
                                           size=n, replace=False))
    if kind == "counter":
        vals = np.cumsum(r.integers(0, 50, n)).astype(np.float64)
        # inject resets left-to-right so counters stay non-negative
        for pos in np.sort(r.choice(n, size=max(1, n // 40), replace=False)):
            vals[pos:] = vals[pos:] - vals[pos] + r.integers(0, 5)
    else:
        vals = r.normal(100, 25, n)
    if with_nans:
        vals[r.choice(n, size=n // 10, replace=False)] = np.nan
    return ts.astype(np.int64), vals


def batch_of(series):
    ts_list = [s[0] for s in series]
    val_list = [s[1] for s in series]
    return build_batch(ts_list, val_list, pad_to=64)


def check(kernel_out, series, fn_name, rtol=1e-9, **params):
    for i, (ts, vals) in enumerate(series):
        expect = oracle.range_fn(fn_name, ts, vals, START, END, STEP, WINDOW, **params)
        got = np.asarray(kernel_out[i])
        np.testing.assert_allclose(got, expect, rtol=rtol, atol=1e-9, equal_nan=True,
                                   err_msg=f"series {i} fn {fn_name}")


@pytest.fixture(scope="module")
def gauge_series():
    return [make_series(n, "gauge", with_nans=(i % 2 == 0), seed=i)
            for i, n in enumerate([50, 80, 120, 30, 7, 2])]


@pytest.fixture(scope="module")
def counter_series():
    return [make_series(n, "counter", seed=100 + i) for i, n in enumerate([60, 90, 150, 10, 3])]


@pytest.fixture(scope="module")
def gauge_batch(gauge_series):
    b = batch_of(gauge_series)
    return jnp.asarray(b.timestamps), jnp.asarray(b.values)


@pytest.fixture(scope="module")
def counter_batch(counter_series):
    b = batch_of(counter_series)
    return jnp.asarray(b.timestamps), jnp.asarray(b.values)


STEPS_J = jnp.asarray(STEPS)


class TestPrefixPath:
    def test_sum_over_time(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.sum_over_time(ts, vals, STEPS_J, WINDOW), gauge_series, "sum_over_time")

    def test_count_over_time(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.count_over_time(ts, vals, STEPS_J, WINDOW), gauge_series, "count_over_time")

    def test_avg_over_time(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.avg_over_time(ts, vals, STEPS_J, WINDOW), gauge_series, "avg_over_time")

    def test_stddev_stdvar(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.stdvar_over_time(ts, vals, STEPS_J, WINDOW), gauge_series,
              "stdvar_over_time", rtol=1e-6)
        check(W.stddev_over_time(ts, vals, STEPS_J, WINDOW), gauge_series,
              "stddev_over_time", rtol=1e-6)

    def test_changes(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.changes_over_time(ts, vals, STEPS_J, WINDOW), gauge_series, "changes")

    def test_resets(self, counter_batch, counter_series):
        ts, vals = counter_batch
        check(W.resets_over_time(ts, vals, STEPS_J, WINDOW), counter_series, "resets")

    def test_last_sample(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        got, _ = W.last_sample(ts, vals, STEPS_J, WINDOW)
        check(got, gauge_series, "last")

    def test_timestamp(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.timestamp_fn(ts, vals, STEPS_J, WINDOW), gauge_series, "timestamp")

    def test_z_score(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.z_score(ts, vals, STEPS_J, WINDOW), gauge_series, "z_score", rtol=1e-6)


class TestRateFamily:
    def test_rate(self, counter_batch, counter_series):
        ts, vals = counter_batch
        check(W.rate(ts, vals, STEPS_J, WINDOW), counter_series, "rate", rtol=1e-9)

    def test_increase(self, counter_batch, counter_series):
        ts, vals = counter_batch
        check(W.increase(ts, vals, STEPS_J, WINDOW), counter_series, "increase")

    def test_delta(self, gauge_batch, gauge_series):
        # delta applies to gauges without counter correction; NaN samples at
        # window boundaries must be skipped (finite-sample bounds)
        ts, vals = gauge_batch
        check(W.delta_fn(ts, vals, STEPS_J, WINDOW), gauge_series, "delta")

    def test_rate_with_nan_samples(self):
        # counters with injected NaN gaps: boundary samples must skip NaN
        series = []
        for i, n in enumerate([60, 90]):
            ts, vals = make_series(n, "counter", seed=300 + i)
            vals[np.random.default_rng(i).choice(n, n // 8, replace=False)] = np.nan
            series.append((ts, vals))
        b = batch_of(series)
        ts, vals = jnp.asarray(b.timestamps), jnp.asarray(b.values)
        check(W.rate(ts, vals, STEPS_J, WINDOW), series, "rate")
        check(W.irate(ts, vals, STEPS_J, WINDOW), series, "irate")

    def test_irate_idelta(self, counter_series):
        b = batch_of(counter_series)
        ts, vals = jnp.asarray(b.timestamps), jnp.asarray(b.values)
        check(W.irate(ts, vals, STEPS_J, WINDOW), counter_series, "irate")
        check(W.idelta(ts, vals, STEPS_J, WINDOW), counter_series, "idelta")

    def test_counter_correction_matches_oracle(self, counter_series):
        for ts, vals in counter_series:
            got = np.asarray(W.counter_correct(jnp.asarray(vals[None, :])))[0]
            np.testing.assert_allclose(got, oracle.counter_correct(vals))
            assert np.all(np.diff(got) >= 0)  # corrected counters are monotonic


WMAX = 128


class TestGatherPath:
    def test_min_max(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.min_over_time(ts, vals, STEPS_J, WINDOW, WMAX), gauge_series, "min_over_time")
        check(W.max_over_time(ts, vals, STEPS_J, WINDOW, WMAX), gauge_series, "max_over_time")

    def test_quantile(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        got = W.quantile_over_time(ts, vals, STEPS_J, WINDOW, WMAX, 0.9)
        check(got, gauge_series, "quantile_over_time", rtol=1e-6, q=0.9)

    def test_deriv(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        check(W.deriv(ts, vals, STEPS_J, WINDOW, WMAX), gauge_series, "deriv", rtol=1e-5)

    def test_predict_linear(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        got = W.predict_linear(ts, vals, STEPS_J, WINDOW, WMAX, 300.0)
        check(got, gauge_series, "predict_linear", rtol=1e-5, duration_s=300.0)

    def test_holt_winters(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        got = W.holt_winters(ts, vals, STEPS_J, WINDOW, WMAX, 0.5, 0.1)
        check(got, gauge_series, "holt_winters", rtol=1e-6, sf=0.5, tf=0.1)

    def test_mad(self, gauge_batch, gauge_series):
        ts, vals = gauge_batch
        got = W.mad_over_time(ts, vals, STEPS_J, WINDOW, WMAX)
        check(got, gauge_series, "mad_over_time", rtol=1e-6)


class TestEdgeCases:
    def test_empty_series_slot(self):
        b = build_batch([np.array([], dtype=np.int64)], [np.array([])], pad_to=8)
        ts, vals = jnp.asarray(b.timestamps), jnp.asarray(b.values)
        out = W.sum_over_time(ts, vals, STEPS_J, WINDOW)
        assert np.isnan(np.asarray(out)).all()
        out = W.rate(ts, vals, STEPS_J, WINDOW)
        assert np.isnan(np.asarray(out)).all()

    def test_single_sample_rate_is_nan(self):
        ts = np.array([START + 1000], dtype=np.int64)
        vals = np.array([5.0])
        b = build_batch([ts], [vals], pad_to=8)
        out = np.asarray(W.rate(jnp.asarray(b.timestamps), jnp.asarray(b.values),
                                STEPS_J, WINDOW))
        assert np.isnan(out).all()

    def test_window_boundary_exclusive_start(self):
        # sample exactly at t-window must be excluded; at t included
        ts = np.array([START - WINDOW, START], dtype=np.int64)
        vals = np.array([1.0, 2.0])
        b = build_batch([ts], [vals], pad_to=8)
        out = np.asarray(W.sum_count_avg(jnp.asarray(b.timestamps),
                                         jnp.asarray(b.values),
                                         jnp.asarray([START]), WINDOW)[0])
        assert out[0, 0] == 2.0  # only the t=START sample
