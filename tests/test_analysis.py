"""filolint engine + the three semantic analyses (ISSUE 8).

Covers:

- engine mechanics: justification-required suppressions, stale-
  suppression detection, unknown rules, meta-rule unsuppressibility;
- a generalized positive/negative fixture over ALL rules (the old
  per-lint ``*_lint_catches_*`` pattern, one table) including the
  seeded PR 11/12 bug shapes (blocking peer POST under a held lock,
  tenant-gauge mutation off the export lock, stall-machine state);
- lock-discipline specifics: ``# guarded-by:`` / ``# holds-lock:``
  annotations, the ``*_locked`` naming convention, Condition aliasing,
  deferred (lambda / nested def) bodies;
- the tier-1 gate: zero unsuppressed findings over filodb_tpu/ under a
  10s wall-clock budget, ``--json`` output shaped for CI, nonzero exit
  on a violation, and the delete-any-suppression / re-introduce-the-
  fixed-bug regressions the acceptance criteria name.
"""

import json
import pathlib
import time

import pytest

import filodb_tpu.analysis as A
from filodb_tpu.analysis.__main__ import main as lint_main

REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = REPO / "filodb_tpu"


def _fake(src, rules, rel="filodb_tpu/fake.py", **kw):
    return A.unsuppressed(A.run_source(src, rules=rules, rel=rel, **kw))


# ---------------------------------------------------------------------------
# engine: suppression discipline
# ---------------------------------------------------------------------------

_BAD_SENTINEL = (
    "def f(self, buf):\n"
    "    self._lib.dd_decode(buf, 1, 2, 3, None, 0){}\n"
)


def test_suppression_needs_matching_rule_and_reason():
    # justified suppression of the right rule: silent
    src = _BAD_SENTINEL.format(
        "  # filolint: disable=decode-sentinel — synthetic input")
    fs = A.run_source(src, rules=["decode-sentinel"])
    assert A.unsuppressed(fs) == []
    sup = [f for f in fs if f.suppressed]
    assert len(sup) == 1 and sup[0].suppress_reason == "synthetic input"


def test_suppression_without_reason_is_an_error():
    src = _BAD_SENTINEL.format("  # filolint: disable=decode-sentinel")
    got = _fake(src, ["decode-sentinel"])
    rules = {f.rule for f in got}
    # the original finding stays visible AND the bare disable is flagged
    assert "decode-sentinel" in rules
    assert A.engine.SUPPRESSION_SYNTAX in rules


def test_stale_suppression_is_an_error():
    src = ("x = 1  # filolint: disable=decode-sentinel — nothing actually "
           "fires here\n")
    got = _fake(src, ["decode-sentinel"])
    assert len(got) == 1 and got[0].rule == A.engine.STALE_SUPPRESSION
    assert "never fires" in got[0].message


def test_stale_only_relative_to_selected_rules():
    """A --rules subset must not condemn other rules' suppressions."""
    src = ("x = 1  # filolint: disable=decode-sentinel — pending\n")
    got = _fake(src, ["timed-handler"])      # decode-sentinel did not run
    assert got == []


def test_unknown_rule_in_disable_is_an_error():
    src = "x = 1  # filolint: disable=no-such-rule — whatever\n"
    got = _fake(src, ["decode-sentinel"])
    assert len(got) == 1 and "unknown rule" in got[0].message


def test_meta_rules_cannot_be_suppressed():
    src = ("x = 1  # filolint: disable=stale-suppression — nice try\n")
    got = _fake(src, ["decode-sentinel"])
    assert any("cannot be suppressed" in f.message for f in got)


def test_multi_rule_disable_comment():
    src = (
        "import urllib.request\n"
        "class C:\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            urllib.request.urlopen(u)  "
        "# filolint: disable=blocking-under-lock,deadline-threading "
        "— test double: both rules fire on this line by design\n"
    )
    fs = A.run_source(src, rules=["blocking-under-lock",
                                  "deadline-threading"])
    assert A.unsuppressed(fs) == []
    assert sum(1 for f in fs if f.suppressed) == 2


def test_unparseable_module_is_reported():
    got = _fake("def broken(:\n", ["decode-sentinel"])
    assert len(got) == 1 and "unparseable" in got[0].message


def test_docstring_mention_is_not_a_directive():
    src = '"""Docs may show # filolint: disable=decode-sentinel — x."""\n'
    assert _fake(src, ["decode-sentinel"]) == []


# ---------------------------------------------------------------------------
# one table of positive/negative snippets for every rule (the old
# *_lint_catches_* pattern, generalized)
# ---------------------------------------------------------------------------

RULE_CASES = [
    ("decode-sentinel",
     "def f(self, buf):\n    self._lib.dd_decode(buf, 1)\n",
     "def f(self, buf):\n    got = self._lib.dd_decode(buf, 1)\n"
     "    if got < 0:\n        raise ValueError\n",
     "sentinel", {}),
    ("timed-handler",
     "class FiloHttpServer:\n"
     "    def _route(self, p, q):\n        return self._dark(q)\n"
     "    def _dark(self, q):\n        return 200, {}\n",
     "class FiloHttpServer:\n"
     "    def _route(self, p, q):\n        return self._lit(q)\n"
     "    @_timed('lit')\n"
     "    def _lit(self, q):\n        return 200, {}\n",
     "histogram", {}),
    ("interpret-coverage",
     "def new_kernel(x, interpret=False):\n    return x\n",
     "def new_kernel(x, interpret=False):\n    return x\n",
     "interpret", {"rel": "filodb_tpu/ops/fake.py",
                   "good_kw": {"test_sources":
                               ["y = new_kernel(a, interpret=True)"]},
                   "bad_kw": {"test_sources": ["z = 1"]}}),
    ("device-put-ledger",
     "import jax\nx = jax.device_put(a, d)\n",
     "from filodb_tpu.utils.devicewatch import LEDGER\n"
     "x = LEDGER.device_put(a, d, owner='o', fmt='dense')\n",
     "ledger", {}),
    ("admission-routing",
     "class FiloHttpServer:\n"
     "    def _exec(self, b, plan):\n"
     "        ep = b.planner.materialize(plan, q)\n"
     "        return ep.execute(ctx)\n",
     "class FiloHttpServer:\n"
     "    def _exec(self, b, plan):\n"
     "        ep = b.planner.materialize(plan, q)\n"
     "        with self._admit(b, ep, q):\n"
     "            return ep.execute(ctx)\n",
     "_admit", {}),
    ("deadline-threading",
     "import urllib.request\n"
     "class MyPlanDispatcher:\n"
     "    def dispatch(self):\n"
     "        urllib.request.urlopen(req, timeout=60.0)\n",
     "import urllib.request\n"
     "class MyPlanDispatcher:\n"
     "    def dispatch(self):\n"
     "        remaining_s = deadline.budget_timeout_s(q, 60.0)\n"
     "        urllib.request.urlopen(req, timeout=remaining_s)\n",
     "deadline", {}),
    ("metric-doc",
     "m = REG.counter('filodb_brand_new_total', 'h')\n",
     "m = REG.counter('filodb_query_request_seconds', 'h')\n",
     "observability.md",
     {"good_kw": {"doc_text": "| `filodb_query_*` | `request_seconds` |"},
      "bad_kw": {"doc_text": "| `filodb_query_*` | `request_seconds` |"}}),
    ("admin-endpoint-documented",
     # same dispatch arm both ways; only the doc table differs — the
     # rule reads the router's parts[i] == "..." compares, never
     # "/admin/..." string literals (the router has none)
     "class FiloHttpServer:\n"
     "    def _route(self, path, params):\n"
     "        parts = path.split('/')\n"
     "        if len(parts) == 2 and parts[0] == 'admin' \\\n"
     "                and parts[1] == 'darkroute':\n"
     "            return self._dark(params)\n",
     "class FiloHttpServer:\n"
     "    def _route(self, path, params):\n"
     "        parts = path.split('/')\n"
     "        if len(parts) == 2 and parts[0] == 'admin' \\\n"
     "                and parts[1] == 'darkroute':\n"
     "            return self._dark(params)\n",
     "http_api.md",
     {"rel": "filodb_tpu/http/server.py",
      "good_kw": {"api_doc_text":
                  "| `GET /admin/darkroute` | dark corner |"},
      "bad_kw": {"api_doc_text":
                 "| `GET /admin/insights` | documented elsewhere |"}}),
    ("evaluator-workload",
     # a background evaluator minting query identity without a
     # workload class or deadline — invisible ambient-priority load
     "class BackgroundEvaluator:\n"
     "    def tick(self):\n"
     "        qctx = QueryContext(submit_time_ms=1)\n"
     "        ep = self.planner.materialize(plan, qctx)\n"
     "        return ep.execute(ctx)\n",
     "from filodb_tpu.workload import deadline as wdl\n"
     "class BackgroundEvaluator:\n"
     "    def tick(self):\n"
     "        qctx = wdl.mint(QueryContext(submit_time_ms=1,\n"
     "                                     priority='rules'))\n"
     "        ep = self.planner.materialize(plan, qctx)\n"
     "        return ep.execute(ctx)\n",
     "priority", {}),
    ("kernel-timer-coverage",
     # the kernel-timer ledger keys on program=; the __name__ fallback
     # forks the ledger row on any rename (ISSUE 15)
     "from filodb_tpu.utils import devicewatch\n"
     "staged = devicewatch.jit(fn)\n",
     "from filodb_tpu.utils import devicewatch\n"
     "staged = devicewatch.jit(fn, program='m.stage')\n",
     "program=", {}),
    ("replica-routing",
     "class MyPlanDispatcher:\n"
     "    def dispatch(self, plan, ctx):\n"
     "        return self.mapper.replica_nodes(plan.shard)[0]\n",
     "class MyPlanDispatcher:\n"
     "    def dispatch(self, plan, ctx):\n"
     "        return self.replica_set.pick(plan.shard)[0]\n",
     "ReplicaSet.pick", {}),
    ("bounded-cache",
     # the PR 11 gateway-memo stampede shape: guarded read + keyed
     # write, nothing ever evicts
     "class SeriesMemo:\n"
     "    def __init__(self):\n"
     "        self._memo = {}\n"
     "    def lookup(self, key):\n"
     "        got = self._memo.get(key)\n"
     "        if got is None:\n"
     "            got = self._memo[key] = self._compute(key)\n"
     "        return got\n",
     "class SeriesMemo:\n"
     "    def __init__(self):\n"
     "        self._memo = {}\n"
     "    def lookup(self, key):\n"
     "        got = self._memo.get(key)\n"
     "        if got is None:\n"
     "            if len(self._memo) > 1000:\n"
     "                self._memo.clear()\n"
     "            got = self._memo[key] = self._compute(key)\n"
     "        return got\n",
     "eviction bound", {"rel": "filodb_tpu/gateway/fake.py"}),
    # --- the three NEW analyses, seeded with the PR 11/12 bug shapes ---
    ("lock-discipline",
     # the _set_tenant_gauges shape: rows mutated off the export lock
     "class TenantGauges:\n"
     "    def __init__(self):\n"
     "        self._rows = {}\n"
     "    def sample(self):\n"
     "        with _EXPORT_LOCK:\n"
     "            self._rows['a'] = 1\n"
     "    def report(self):\n"
     "        with _EXPORT_LOCK:\n"
     "            self._rows.pop('a', None)\n"
     "    def clobber(self):\n"
     "        self._rows.clear()\n",
     "class TenantGauges:\n"
     "    def __init__(self):\n"
     "        self._rows = {}\n"
     "    def sample(self):\n"
     "        with _EXPORT_LOCK:\n"
     "            self._rows['a'] = 1\n"
     "    def report(self):\n"
     "        with _EXPORT_LOCK:\n"
     "            self._rows.pop('a', None)\n"
     "    def clobber(self):\n"
     "        with _EXPORT_LOCK:\n"
     "            self._rows.clear()\n",
     "does not hold it", {}),
    ("blocking-under-lock",
     # the ReplicaFanout wedge: a blocking peer POST inside the lock
     "import urllib.request\n"
     "class ReplicaFanout:\n"
     "    def publish(self, container):\n"
     "        with self._lock:\n"
     "            urllib.request.urlopen(req, timeout=self.timeout_s)\n",
     "import urllib.request\n"
     "class ReplicaFanout:\n"
     "    def publish(self, container):\n"
     "        with self._lock:\n"
     "            lanes = list(self._lanes)\n"
     "        urllib.request.urlopen(req, timeout=self.timeout_s)\n",
     "convoy", {}),
    ("resource-lifecycle",
     "class T:\n"
     "    def start(self):\n"
     "        g = registry.gauge('x')\n"
     "        g.set_fn(self._sample, shard=1)\n",
     "class T:\n"
     "    def start(self):\n"
     "        g = registry.gauge('x')\n"
     "        g.set_fn(self._sample, shard=1)\n"
     "    def close(self):\n"
     "        registry.gauge('x').remove(shard=1)\n",
     "Gauge.remove", {}),
    # --- ISSUE 10: lock order + device discipline ---
    ("lock-order-cycle",
     # the shard/index AB/BA shape: freeze takes shard->index while
     # evict takes index->shard — two threads deadlock
     "class TimeSeriesShard:\n"
     "    def freeze(self):\n"
     "        with self._shard_lock:\n"
     "            with self._index_lock:\n"
     "                pass\n"
     "    def evict(self):\n"
     "        with self._index_lock:\n"
     "            with self._shard_lock:\n"
     "                pass\n",
     "class TimeSeriesShard:\n"
     "    def freeze(self):\n"
     "        with self._shard_lock:\n"
     "            with self._index_lock:\n"
     "                pass\n"
     "    def evict(self):\n"
     "        with self._shard_lock:\n"
     "            with self._index_lock:\n"
     "                pass\n",
     "deadlock", {}),
    ("lock-order-inversion",
     "class Part:\n"
     "    def __init__(self):\n"
     "        # lock-order: _encode_lock < _buf_lock\n"
     "        self._buf_lock = mk()\n"
     "    def bad(self):\n"
     "        with self._buf_lock:\n"
     "            with self._encode_lock:\n"
     "                pass\n",
     "class Part:\n"
     "    def __init__(self):\n"
     "        # lock-order: _encode_lock < _buf_lock\n"
     "        self._buf_lock = mk()\n"
     "    def good(self):\n"
     "        with self._encode_lock:\n"
     "            with self._buf_lock:\n"
     "                pass\n",
     "declares", {}),
    ("host-sync",
     "import numpy as np\n"
     "from filodb_tpu.utils import devicewatch\n"
     "@devicewatch.jit\n"
     "def prog(x):\n"
     "    return x\n"
     "def serve(x):\n"
     "    out = prog(x)\n"
     "    return np.asarray(out)\n",
     "import numpy as np\n"
     "from filodb_tpu.utils import devicewatch\n"
     "@devicewatch.jit\n"
     "def prog(x):\n"
     "    return x\n"
     "def serve(x):\n"
     "    out = prog(x)\n"
     "    return np.asarray(out)  # host-sync-ok: the one designed "
     "readback for serialization\n",
     "readback", {"rel": "filodb_tpu/query/fake.py"}),
    ("host-sync-annotation",
     # an annotation on a line with no detected sync is stale
     "x = 1  # host-sync-ok: nothing here\n",
     "import numpy as np\n"
     "from filodb_tpu.utils import devicewatch\n"
     "@devicewatch.jit\n"
     "def prog(x):\n"
     "    return x\n"
     "def serve(x):\n"
     "    out = prog(x)\n"
     "    return np.asarray(out)  # host-sync-ok: designed readback\n",
     "stale", {"rel": "filodb_tpu/query/fake.py"}),
    ("recompile-hazard",
     # a jit call site keyed on a Python len(...): every distinct
     # series count traces a fresh program (the PR 9 storm shape)
     "from filodb_tpu.utils import devicewatch\n"
     "@devicewatch.jit\n"
     "def prog(x, nrows):\n"
     "    return x\n"
     "def serve(rows, x):\n"
     "    return prog(x, len(rows))\n",
     "import functools\n"
     "from filodb_tpu.utils import devicewatch\n"
     "@functools.partial(devicewatch.jit, static_argnames=('nrows',))\n"
     "def prog(x, *, nrows):\n"
     "    return x\n"
     "def serve(rows, x):\n"
     "    return prog(x, nrows=len(rows))\n",
     "static_argnames", {}),
    ("vmem-budget",
     # 2 x 4096x4096 f32 blocks = 128 MiB per grid step
     "import jax\n"
     "import jax.numpy as jnp\n"
     "from jax.experimental import pallas as pl\n"
     "def kern(x_ref, o_ref):\n"
     "    o_ref[...] = x_ref[...]\n"
     "def big(x):\n"
     "    return pl.pallas_call(\n"
     "        kern,\n"
     "        out_shape=jax.ShapeDtypeStruct((4096, 4096), jnp.float32),\n"
     "        in_specs=[pl.BlockSpec((4096, 4096), lambda i: (0, 0))],\n"
     "        out_specs=pl.BlockSpec((4096, 4096), lambda i: (0, 0)),\n"
     "    )(x)\n",
     "import jax\n"
     "import jax.numpy as jnp\n"
     "from jax.experimental import pallas as pl\n"
     "def kern(x_ref, o_ref):\n"
     "    o_ref[...] = x_ref[...]\n"
     "def small(x):\n"
     "    return pl.pallas_call(\n"
     "        kern,\n"
     "        out_shape=jax.ShapeDtypeStruct((4096, 4096), jnp.float32),\n"
     "        in_specs=[pl.BlockSpec((256, 1024), lambda i: (i, 0))],\n"
     "        out_specs=pl.BlockSpec((256, 1024), lambda i: (i, 0)),\n"
     "    )(x)\n",
     "VMEM", {}),
    ("batch-admission-discipline",
     # a group executor stacking members and launching the vmapped
     # program without consulting permits or deadline budgets
     "def launch_group(self, g, batch_launch):\n"
     "    row0s = [m.row0 for m in g.members]\n"
     "    return batch_launch(row0s)\n",
     "def launch_group(self, g, batch_launch):\n"
     "    live = [m for m in g.members\n"
     "            if not m.qctx.admission_permit.released\n"
     "            and remaining_ms(m.qctx) > 0]\n"
     "    return batch_launch([m.row0 for m in live])\n",
     "admission_permit", {}),
]


@pytest.mark.parametrize(
    "rule,bad,good,match,extra",
    RULE_CASES, ids=[c[0] for c in RULE_CASES])
def test_rule_fires_on_bad_and_accepts_good(rule, bad, good, match, extra):
    rel = extra.get("rel", "filodb_tpu/fake.py")
    got = _fake(bad, [rule], rel=rel, **extra.get("bad_kw", {}))
    assert got, f"{rule}: did not fire on the bad shape"
    assert all(f.rule == rule for f in got)
    assert any(match in f.message for f in got), \
        f"{rule}: message lacks {match!r}: {got[0].message}"
    assert _fake(good, [rule], rel=rel, **extra.get("good_kw", {})) == [], \
        f"{rule}: false positive on the good shape"


# ---------------------------------------------------------------------------
# lock-discipline specifics
# ---------------------------------------------------------------------------


def test_guarded_by_annotation_flags_reads_and_writes():
    src = (
        "class StallMachine:\n"
        "    def __init__(self):\n"
        "        self._stall = {}  # guarded-by: _lock\n"
        "    def sample(self):\n"
        "        with self._lock:\n"
        "            self._stall['k'] = 1\n"
        "    def peek(self):\n"
        "        return self._stall.get('k')\n"
    )
    got = _fake(src, ["lock-discipline"])
    assert len(got) == 1 and "read here without holding" in got[0].message
    fixed = src.replace(
        "        return self._stall.get('k')\n",
        "        with self._lock:\n"
        "            return self._stall.get('k')\n")
    assert _fake(fixed, ["lock-discipline"]) == []


def test_bounded_cache_scoped_to_serving_paths():
    """The same unbounded memo outside the serving prefixes (analysis
    tooling, tests, utils) is not a stampede surface and stays silent."""
    src = ("class M:\n"
           "    def __init__(self):\n"
           "        self._memo = {}\n"
           "    def get(self, k):\n"
           "        if k not in self._memo:\n"
           "            self._memo[k] = 1\n"
           "        return self._memo[k]\n")
    assert _fake(src, ["bounded-cache"],
                 rel="filodb_tpu/gateway/fake.py") != []
    assert _fake(src, ["bounded-cache"],
                 rel="filodb_tpu/analysis/fake.py") == []


def test_bounded_cache_accepts_evict_helper_and_module_memos():
    """Handing the memo to an evict/prune helper (the gateway
    evict_memo_half shape) is a bound; module-level memos are checked
    with the same shape rules."""
    helper = ("def lookup(self, k):\n"
              "    got = self._memo.get(k)\n"
              "    if got is None:\n"
              "        evict_memo_half(self._memo)\n"
              "        got = self._memo[k] = compute(k)\n"
              "    return got\n")
    src = ("class M:\n"
           "    def __init__(self):\n"
           "        self._memo = {}\n" + "    " +
           helper.replace("\n", "\n    ").rstrip() + "\n")
    assert _fake(src, ["bounded-cache"],
                 rel="filodb_tpu/gateway/fake.py") == []
    mod = ("_MEMO = {}\n"
           "def lookup(k):\n"
           "    got = _MEMO.get(k)\n"
           "    if got is None:\n"
           "        got = _MEMO[k] = compute(k)\n"
           "    return got\n")
    got = _fake(mod, ["bounded-cache"], rel="filodb_tpu/query/fake.py")
    assert got and "module scope" in got[0].message


def test_kernel_timer_coverage_unique_across_modules():
    """Two entry points sharing one program name merge their device-time
    ledger rows — the duplicate check is whole-program (ISSUE 15)."""
    a = ("from filodb_tpu.utils import devicewatch\n"
         "f = devicewatch.jit(fn, program='grid.x')\n")
    b = ("from filodb_tpu.utils import devicewatch\n"
         "g = devicewatch.jit(fn2, program='grid.x')\n")
    got = A.unsuppressed(A.run_sources(
        {"filodb_tpu/ops/a.py": a, "filodb_tpu/ops/b.py": b},
        rules=["kernel-timer-coverage"]))
    assert len(got) == 1 and "duplicate" in got[0].message \
        and "ops/a.py" in got[0].message
    got = A.unsuppressed(A.run_sources(
        {"filodb_tpu/ops/a.py": a,
         "filodb_tpu/ops/b.py": b.replace("'grid.x'", "'grid.y'")},
        rules=["kernel-timer-coverage"]))
    assert got == []


def test_kernel_timer_coverage_forms():
    """Bare decorators and partial() decorators without program=, and
    computed (non-literal) names, all fire; devicewatch.py itself (the
    wrapper's home, whose docstring/recursion spell jit bare) is
    exempt."""
    bare = ("from filodb_tpu.utils import devicewatch\n"
            "@devicewatch.jit\n"
            "def prog(x):\n    return x\n")
    got = _fake(bare, ["kernel-timer-coverage"])
    assert got and "program=" in got[0].message
    partial_bad = ("import functools\n"
                   "from filodb_tpu.utils import devicewatch\n"
                   "@functools.partial(devicewatch.jit,\n"
                   "                   static_argnames=('q',))\n"
                   "def prog(x, *, q):\n    return x\n")
    assert _fake(partial_bad, ["kernel-timer-coverage"])
    partial_ok = partial_bad.replace(
        "static_argnames=('q',)",
        "program='ops.prog', static_argnames=('q',)")
    assert _fake(partial_ok, ["kernel-timer-coverage"]) == []
    computed = ("from filodb_tpu.utils import devicewatch\n"
                "f = devicewatch.jit(fn, program='pfx.' + name)\n")
    got = _fake(computed, ["kernel-timer-coverage"])
    assert got and "string literal" in got[0].message
    assert _fake(bare, ["kernel-timer-coverage"],
                 rel="filodb_tpu/utils/devicewatch.py") == []


def test_dangling_guarded_by_annotation_is_an_error():
    """A guarded-by comment that binds to no attribute assignment must
    fail loudly, not silently disarm the race detector."""
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        pass  # guarded-by: _lock\n"
    )
    got = _fake(src, ["lock-discipline"])
    assert len(got) == 1 and "binds to nothing" in got[0].message


def test_holds_lock_annotation_and_locked_suffix():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        self._m = {}  # guarded-by: _lock\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._apply_locked()\n"
        "    def _apply_locked(self):\n"
        "        self._m['x'] = 1\n"
        "    def _sweep(self):  # holds-lock: _lock\n"
        "        self._m.clear()\n"
    )
    assert _fake(src, ["lock-discipline"]) == []


def test_condition_aliases_its_lock():
    src = (
        "import threading\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._pending = []  # guarded-by: _lock\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "    def put(self, x):\n"
        "        with self._cv:\n"
        "            self._pending.append(x)\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            self._pending.clear()\n"
    )
    assert _fake(src, ["lock-discipline"]) == []


def test_deferred_bodies_do_not_inherit_the_lock():
    """A lambda/def registered under a lock runs later WITHOUT it —
    the walker must not treat its body as locked (a blocking call in a
    set_fn callback registered under a lock is fine)."""
    src = (
        "import urllib.request\n"
        "class C:\n"
        "    def start(self):\n"
        "        with self._lock:\n"
        "            self._cb = lambda: urllib.request.urlopen(u)\n"
    )
    assert _fake(src, ["blocking-under-lock"]) == []


def test_blocking_propagates_through_local_helpers():
    src = (
        "import time\n"
        "class C:\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self._hop1()\n"
        "    def _hop1(self):\n"
        "        self._hop2()\n"
        "    def _hop2(self):\n"
        "        time.sleep(1)\n"
    )
    got = _fake(src, ["blocking-under-lock"])
    assert len(got) == 1
    assert "via _hop1 -> _hop2" in got[0].message


def test_future_result_and_thread_join_under_lock():
    src = (
        "class C:\n"
        "    def a(self, fut, t):\n"
        "        with self._lock:\n"
        "            x = fut.result(timeout=5)\n"
        "            t.join()\n"
        "    def b(self, parts):\n"
        "        with self._lock:\n"
        "            return ','.join(parts)\n"     # str.join: not blocking
    )
    got = _fake(src, ["blocking-under-lock"])
    assert len(got) == 2


def test_lifecycle_periodic_thread_and_finalize_and_pool():
    thread_bad = (
        "class S:\n"
        "    def start(self):\n"
        "        self._loop = PeriodicThread(self.tick, 5.0)\n"
    )
    got = _fake(thread_bad, ["resource-lifecycle"])
    assert len(got) == 1 and "PeriodicThread" in got[0].message
    thread_good = thread_bad + (
        "    def close(self):\n"
        "        self._loop.stop()\n")
    assert _fake(thread_good, ["resource-lifecycle"]) == []

    fin_bad = (
        "import weakref\n"
        "class L:\n"
        "    def track(self, arr):\n"
        "        weakref.finalize(arr, self._cb, 1)\n"
    )
    got = _fake(fin_bad, ["resource-lifecycle"])
    assert len(got) == 1 and "finalize" in got[0].message
    fin_good = fin_bad + (
        "    def untrack(self, key):\n"
        "        self._fins.pop(key, None)\n")
    assert _fake(fin_good, ["resource-lifecycle"]) == []

    pool_bad = (
        "class Sh:\n"
        "    def start(self):\n"
        "        LEDGER.register_pool('o', lambda: 0)\n"
    )
    got = _fake(pool_bad, ["resource-lifecycle"])
    assert len(got) == 1 and "deregister_pool" in got[0].message
    pool_good = pool_bad + (
        "    def close(self):\n"
        "        LEDGER.deregister_pool('o')\n")
    assert _fake(pool_good, ["resource-lifecycle"]) == []


# ---------------------------------------------------------------------------
# the tier-1 gate: whole-tree run, budget, JSON, exit codes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tree_findings():
    t0 = time.monotonic()
    findings = A.run_paths([PKG])
    elapsed = time.monotonic() - t0
    return findings, elapsed


def test_full_tree_zero_unsuppressed_under_budget(tree_findings):
    findings, elapsed = tree_findings
    bad = A.unsuppressed(findings)
    assert not bad, "unsuppressed findings:\n  " + "\n  ".join(
        f"{f.where()}: [{f.rule}] {f.message}" for f in bad)
    # every suppression that exists is justified (non-empty reason)
    for f in findings:
        if f.suppressed:
            assert f.suppress_reason.strip()
    # budget raised 10s -> 15s in PR 17: the tree grew to 126+ files
    # (typical run ~4-5s, vs 2.4s when PR 13 set 10s) and single-core
    # CI boxes spike 2x under load — the guard still catches any
    # super-linear regression without flaking on host noise
    assert elapsed <= 15.0, \
        f"filolint full-tree run took {elapsed:.1f}s (budget 15s)"


def test_cli_json_output_for_ci(capsys):
    rc = lint_main([str(PKG), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["summary"]["findings"] == 0
    assert doc["summary"]["files"] >= 100
    assert doc["summary"]["suppressed"] >= 1
    for f in doc["findings"]:
        assert {"rule", "path", "line", "message", "severity",
                "suppressed", "suppress_reason"} <= set(f)


def test_cli_nonzero_on_violation(tmp_path, capsys):
    bad = tmp_path / "wedge.py"
    bad.write_text(
        "import urllib.request\n"
        "class ReplicaFanout:\n"
        "    def publish(self, c):\n"
        "        with self._lock:\n"
        "            urllib.request.urlopen(req, timeout=5)\n")
    assert lint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "blocking-under-lock" in out


def test_overlapping_paths_do_not_double_load(capsys):
    """A dir + a file inside it must not load the module twice — the
    duplicate's suppressions would report as falsely stale."""
    target = PKG / "native" / "baseline.py"   # carries a suppression
    rc = lint_main([str(PKG / "native"), str(target), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0, doc["summary"]
    assert doc["summary"]["findings"] == 0


def test_match_statement_bodies_are_walked():
    src = (
        "import time\n"
        "class C:\n"
        "    def f(self, x):\n"
        "        with self._lock:\n"
        "            match x:\n"
        "                case 1:\n"
        "                    time.sleep(5)\n"
    )
    got = _fake(src, ["blocking-under-lock"])
    assert len(got) == 1 and "sleep" in got[0].message


def test_cli_lint_verb_passes_through(capsys):
    from filodb_tpu.cli import main as cli_main
    rc = cli_main(["lint", str(PKG / "analysis"), "--show-suppressed",
                   "--rules", "decode-sentinel"])
    out = capsys.readouterr().out
    assert rc == 0 and "filolint:" in out


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in ("lock-discipline", "blocking-under-lock",
                 "resource-lifecycle", "decode-sentinel", "metric-doc"):
        assert name in out


def test_deleting_any_suppression_makes_it_fail(tree_findings):
    """Acceptance: deleting any ONE suppression comment flips the tree
    run nonzero — i.e. every suppression in the tree covers a finding
    that would otherwise fire right there."""
    findings, _ = tree_findings
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "expected at least one justified suppression"
    for f in suppressed:
        path = REPO / f.path
        lines = path.read_text().splitlines(keepends=True)
        ln = lines[f.line - 1]
        assert "# filolint:" in ln, (f.path, f.line)
        lines[f.line - 1] = ln[:ln.index("# filolint:")].rstrip() + "\n"
        got = _fake("".join(lines), [f.rule], rel=f.path)
        assert any(g.rule == f.rule and g.line == f.line for g in got), \
            f"stripping the suppression at {f.where()} did not re-fire " \
            f"{f.rule}"


# ---------------------------------------------------------------------------
# ISSUE 10: whole-program analyses (call graph, lock order, device)
# ---------------------------------------------------------------------------

_WEDGE_CALLER = (
    "from filodb_tpu.gateway.lanes import deliver\n"
    "class ReplicaFanout:\n"
    "    def publish(self, container):\n"
    "        with self._lock:\n"
    "            deliver(container)\n"
)
_WEDGE_HELPER = (
    "from filodb_tpu.utils.observability import http_container_push\n"
    "def deliver(container):\n"
    "    http_container_push('http://peer', container, timeout_s=5)\n"
)


def test_cross_module_blocking_requires_whole_program():
    """Acceptance: the PR 12 ReplicaFanout wedge SPLIT ACROSS TWO
    MODULES — a ``with self._lock:`` whose blocking peer POST lives in
    another module — is caught by the whole-program fixpoint and
    provably NOT caught by a same-module-only run (this regression
    pins the improvement over PR 13's per-module analysis)."""
    # same-module-only: each module linted alone is silent — the caller
    # cannot resolve deliver(), the helper holds no lock
    assert _fake(_WEDGE_CALLER, ["blocking-under-lock"],
                 rel="filodb_tpu/gateway/fanout.py") == []
    assert _fake(_WEDGE_HELPER, ["blocking-under-lock"],
                 rel="filodb_tpu/gateway/lanes.py") == []
    # whole-program: the same two sources linted TOGETHER fire at the
    # lock-taking caller, with the cross-module chain in the message
    got = A.unsuppressed(A.run_sources(
        {"filodb_tpu/gateway/fanout.py": _WEDGE_CALLER,
         "filodb_tpu/gateway/lanes.py": _WEDGE_HELPER},
        rules=["blocking-under-lock"]))
    assert len(got) == 1
    f = got[0]
    assert f.path == "filodb_tpu/gateway/fanout.py" and f.line == 5
    assert "http_container_push" in f.message
    assert "via lanes.deliver" in f.message


def test_self_attr_call_resolves_through_init_class():
    """``self.x.m()`` where __init__ assigned x a known class resolves
    cross-module (best-effort attribute typing)."""
    caller = (
        "from filodb_tpu.coordinator.lanes import PeerLane\n"
        "class Fanout:\n"
        "    def __init__(self):\n"
        "        self._lane = PeerLane()\n"
        "    def publish(self, c):\n"
        "        with self._lock:\n"
        "            self._lane.deliver(c)\n"
    )
    helper = (
        "import time\n"
        "class PeerLane:\n"
        "    def deliver(self, c):\n"
        "        time.sleep(1)\n"
    )
    got = A.unsuppressed(A.run_sources(
        {"filodb_tpu/coordinator/fanout.py": caller,
         "filodb_tpu/coordinator/lanes.py": helper},
        rules=["blocking-under-lock"]))
    assert len(got) == 1 and got[0].line == 7
    assert "sleep" in got[0].message


def test_cross_module_lock_order_cycle():
    moda = (
        "import threading\n"
        "from filodb_tpu.memstore.other import grab_b\n"
        "_A_LOCK = threading.Lock()\n"
        "def fwd():\n"
        "    with _A_LOCK:\n"
        "        grab_b()\n"
        "def take_a():\n"
        "    with _A_LOCK:\n"
        "        pass\n"
    )
    modb = (
        "import threading\n"
        "from filodb_tpu.memstore.faker import take_a\n"
        "_B_LOCK = threading.Lock()\n"
        "def grab_b():\n"
        "    with _B_LOCK:\n"
        "        pass\n"
        "def rev():\n"
        "    with _B_LOCK:\n"
        "        take_a()\n"
    )
    got = A.unsuppressed(A.run_sources(
        {"filodb_tpu/memstore/faker.py": moda,
         "filodb_tpu/memstore/other.py": modb},
        rules=["lock-order-cycle"]))
    assert len(got) == 1
    assert "_A_LOCK" in got[0].message and "_B_LOCK" in got[0].message
    # each module alone sees only its own half — no cycle
    assert _fake(moda, ["lock-order-cycle"],
                 rel="filodb_tpu/memstore/faker.py") == []
    assert _fake(modb, ["lock-order-cycle"],
                 rel="filodb_tpu/memstore/other.py") == []


def test_lock_order_proactive_declaration_binds_to_acquired_locks():
    """A declaration over two locks that are each acquired but never
    yet nested (the advertised proactive workflow) must NOT read as
    binding to nothing."""
    src = (
        "class A:\n"
        "    def f(self):\n"
        "        # lock-order: _a_lock < _b_lock\n"
        "        with self._a_lock:\n"
        "            pass\n"
        "class B:\n"
        "    def g(self):\n"
        "        with self._b_lock:\n"
        "            pass\n"
    )
    assert _fake(src, ["lock-order-inversion"]) == []


def test_host_sync_ok_in_docstring_is_not_an_annotation():
    """A docstring QUOTING the annotation syntax is neither a live
    annotation nor a stale one (comment-token discipline, same as the
    engine's suppression scanner)."""
    src = (
        '"""Declare readbacks with ``# host-sync-ok: <reason>``."""\n'
        "x = 1\n"
    )
    assert _fake(src, ["host-sync-annotation"],
                 rel="filodb_tpu/query/fake.py") == []


def test_same_named_plain_function_is_not_a_jit_entry():
    """A nested jit closure must not hijack name resolution for an
    unrelated same-named module-level function."""
    src = (
        "from filodb_tpu.utils import devicewatch\n"
        "def factory():\n"
        "    @devicewatch.jit\n"
        "    def kernel(a):\n"
        "        return a\n"
        "    return kernel\n"
        "def kernel(rows, cols):\n"
        "    return rows * cols\n"
        "def serve(xs):\n"
        "    return kernel(len(xs), 4)\n"
    )
    assert _fake(src, ["recompile-hazard"]) == []


def test_lock_order_dangling_declaration_is_an_error():
    src = (
        "class C:\n"
        "    def __init__(self):\n"
        "        # lock-order: _no_such_lock < _lock\n"
        "        self._lock = mk()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    got = _fake(src, ["lock-order-inversion"])
    assert any("binds to nothing" in f.message for f in got)


def test_lock_order_declaration_pins_real_partition_edge():
    """Flipping the in-tree declared encode->buffer order must fire
    against the REAL acquisition edge in partition.py."""
    src = (REPO / "filodb_tpu/memstore/partition.py").read_text()
    decl = "# lock-order: _encode_lock < TimeSeriesPartition._lock"
    assert decl in src
    flipped = src.replace(
        decl, "# lock-order: TimeSeriesPartition._lock < _encode_lock")
    got = _fake(flipped, ["lock-order-inversion"],
                rel="filodb_tpu/memstore/partition.py")
    assert any("_encode_lock" in f.message for f in got)
    assert _fake(src, ["lock-order-inversion"],
                 rel="filodb_tpu/memstore/partition.py") == []


def test_stripping_any_host_sync_ok_refires():
    """Every # host-sync-ok annotation this PR seeded covers a live
    host-sync finding — stripping any one re-fires it (the delete-any-
    suppression sweep, extended to the device allowlist)."""
    total = 0
    for rel in ("filodb_tpu/memstore/devicestore.py",
                "filodb_tpu/parallel/mesh.py",
                "filodb_tpu/parallel/meshgrid.py"):
        src = (REPO / rel).read_text()
        lines = src.splitlines(keepends=True)
        marks = [i for i, ln in enumerate(lines) if "# host-sync-ok:" in ln]
        assert marks, f"{rel}: expected seeded annotations"
        total += len(marks)
        for i in marks:
            stripped = lines[:]
            stripped[i] = stripped[i][
                :stripped[i].index("# host-sync-ok:")].rstrip() + "\n"
            got = _fake("".join(stripped), ["host-sync"], rel=rel)
            assert any(g.line == i + 1 for g in got), \
                f"stripping {rel}:{i + 1} did not re-fire host-sync"
        # and the file as-is is clean (annotations used, none stale)
        assert _fake(src, ["host-sync", "host-sync-annotation"],
                     rel=rel) == []
    assert total >= 19


def test_recompile_hazard_via_local_fstring_binding():
    src = (
        "from filodb_tpu.utils import devicewatch\n"
        "@devicewatch.jit\n"
        "def prog(x, tag):\n"
        "    return x\n"
        "def serve(xs, x):\n"
        "    for i, _ in enumerate(xs):\n"
        "        key = f'k{i}'\n"
        "        prog(x, key)\n"
    )
    got = _fake(src, ["recompile-hazard"])
    assert len(got) == 1 and "f-string" in got[0].message


def test_vmem_budget_knob_and_scratch(tmp_path, capsys):
    from filodb_tpu.analysis import device as D
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def kern(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def f(x):\n"
        "    return pl.pallas_call(\n"
        "        kern,\n"
        "        out_shape=jax.ShapeDtypeStruct((256, 1024), jnp.float32),\n"
        "        in_specs=[pl.BlockSpec((256, 1024), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((256, 1024), lambda i: (i, 0)),\n"
        "    )(x)\n"
    )
    # 2 MiB of blocks: clean at the 16 MiB default, over a 1 MiB budget
    assert _fake(src, ["vmem-budget"]) == []
    p = tmp_path / "k.py"
    p.write_text(src)
    try:
        assert lint_main([str(p), "--vmem-budget-mib", "1"]) == 1
        out = capsys.readouterr().out
        assert "vmem-budget" in out
    finally:
        D.VMEM_BUDGET_BYTES = D.DEFAULT_VMEM_BUDGET_BYTES
    assert lint_main([str(p)]) == 0
    capsys.readouterr()


def test_unresolvable_dims_do_not_fire():
    """Variable BlockSpec dims (the real grid.py shape) are skipped —
    the rule under-counts rather than guessing."""
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def f(x, nb, lanes, kern):\n"
        "    return pl.pallas_call(\n"
        "        kern,\n"
        "        out_shape=jax.ShapeDtypeStruct((nb, lanes), jnp.float32),\n"
        "        in_specs=[pl.BlockSpec((nb, lanes), lambda i: (0, i))],\n"
        "        out_specs=pl.BlockSpec((nb, lanes), lambda i: (0, i)),\n"
        "    )(x)\n"
    )
    assert _fake(src, ["vmem-budget"]) == []


# ---------------------------------------------------------------------------
# ISSUE 10 satellites: --changed, --format=github, exit codes
# ---------------------------------------------------------------------------


def test_exit_code_2_on_usage_errors(capsys):
    assert lint_main(["--rules", "no-such-rule"]) == 2
    assert lint_main([str(PKG / "analysis"),
                      "--changed", "not-a-real-ref"]) == 2
    capsys.readouterr()


def test_format_github_annotations(tmp_path, capsys):
    bad = tmp_path / "wedge.py"
    bad.write_text(
        "import urllib.request\n"
        "class ReplicaFanout:\n"
        "    def publish(self, c):\n"
        "        with self._lock:\n"
        "            urllib.request.urlopen(req, timeout=5)\n")
    assert lint_main([str(bad), "--format=github"]) == 1
    out = capsys.readouterr().out
    assert "::error file=wedge.py,line=5,title=filolint" \
           "[blocking-under-lock]::" in out
    assert "::notice::filolint: " in out


def test_changed_subset_scopes_report(capsys):
    """--changed reports ONLY findings in changed files while the
    analysis still runs whole-program; an untracked violation file is
    picked up, and nothing else (incl. stale-suppression verdicts for
    unchanged files) leaks into the report."""
    probe = PKG / "_filolint_changed_probe.py"
    probe.write_text(
        "import urllib.request\n"
        "class ReplicaFanout:\n"
        "    def publish(self, c):\n"
        "        with self._lock:\n"
        "            urllib.request.urlopen(req, timeout=5)\n")
    try:
        rc = lint_main(["--changed", "HEAD", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        open_findings = [f for f in doc["findings"]
                         if not f["suppressed"]]
        assert open_findings, "probe violation not reported"
        probe_rel = "filodb_tpu/_filolint_changed_probe.py"
        assert {f["path"] for f in open_findings} <= {probe_rel}
    finally:
        probe.unlink()
    # with the probe gone the changed-subset run is clean again
    rc = lint_main(["--changed", "HEAD"])
    capsys.readouterr()
    assert rc == 0


def test_cli_lint_forwards_changed_and_format(capsys):
    """cli.py lint must not hand-mirror flags: the new --changed /
    --format options pass straight through."""
    from filodb_tpu.cli import main as cli_main
    rc = cli_main(["lint", "--changed", "HEAD", "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 0 and "::notice::filolint:" in out


def test_reintroducing_fixed_races_fails_the_build():
    """Acceptance: the exact bug shapes this PR fixed fail the build if
    they come back."""
    # 1. StatusPoller.stop clearing _change_pending off _hook_lock
    src = (REPO / "filodb_tpu/coordinator/cluster.py").read_text()
    locked = ("        with self._hook_lock:\n"
              "            self._change_pending.clear()\n")
    assert locked in src
    regressed = src.replace(
        locked, "        self._change_pending.clear()\n")
    got = _fake(regressed, ["lock-discipline"],
                rel="filodb_tpu/coordinator/cluster.py")
    assert any("_change_pending" in g.message for g in got)
    assert _fake(src, ["lock-discipline"],
                 rel="filodb_tpu/coordinator/cluster.py") == []

    # 2. the ODP page-cache pool losing its deregistration path
    src = (REPO / "filodb_tpu/memstore/odp.py").read_text()
    dereg = "LEDGER.deregister_pool(self._ledger_owner)"
    assert dereg in src
    regressed = src.replace(dereg, "pass")
    got = _fake(regressed, ["resource-lifecycle"],
                rel="filodb_tpu/memstore/odp.py")
    assert any("deregister_pool" in g.message for g in got)

    # 3. _SqliteBase.shutdown resetting DDL state off _ddl_lock
    src = (REPO / "filodb_tpu/store/persistence.py").read_text()
    assert "self._ddl_done = False  # guarded-by: _ddl_lock" in src
    regressed = src.replace(
        "        with self._ddl_lock:\n"
        "            mem = getattr(self, \"_mem_conn\", None)",
        "        if True:\n"
        "            mem = getattr(self, \"_mem_conn\", None)")
    got = _fake(regressed, ["lock-discipline"],
                rel="filodb_tpu/store/persistence.py")
    assert any("_ddl_done" in g.message for g in got)
