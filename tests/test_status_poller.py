"""StatusPoller: dynamic leadership, leader-only reassignment, sticky
operator statuses (reference: cluster singleton + ShardMapper snapshot
gossip; Akka failure detector)."""

import threading
import time

import pytest

from filodb_tpu.coordinator.cluster import (FailureDetector, ShardManager,
                                            StatusPoller)
from filodb_tpu.parallel.shardmap import ShardStatus


def _mk(local, peers, timeout_ms=1_000):
    clock = {"t": 0.0}
    mgr = ShardManager()
    det = FailureDetector(mgr, timeout_ms=timeout_ms,
                          clock=lambda: clock["t"])
    poller = StatusPoller(mgr, det, peers, local, timeout_s=0.2)
    return mgr, det, poller, clock


class TestLeadership:
    def test_lowest_fresh_node_leads(self):
        mgr, det, poller, clock = _mk("node-b", {"node-a": "http://x"})
        det.heartbeat("node-a")
        assert poller.leader == "node-a"
        # node-a's heartbeat goes stale: node-b takes over
        clock["t"] += 2.0
        assert poller.leader == "node-b"
        poller.stop()

    def test_only_leader_declares_down(self):
        # non-leader with a live leader never runs check()
        mgr, det, poller, clock = _mk(
            "node-b", {"node-a": "http://127.0.0.1:1",
                       "node-c": "http://127.0.0.1:1"})
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        det.heartbeat("node-a")
        det.heartbeat("node-c")
        clock["t"] += 0.5
        # node-c would be stale at 1.5 with timeout 1.0...
        clock["t"] += 1.0
        # node-a is ALSO stale now, so node-b becomes acting leader and
        # may declare both; rewind node-a's freshness first
        det.heartbeat("node-a")
        assert poller.leader == "node-a"
        down = poller.poll_once()   # peers unreachable, but a is fresh
        assert down == []           # non-leader: no down declarations
        assert "node-c" in det.alive()
        poller.stop()

    def test_leader_failover_reassigns(self):
        mgr, det, poller, clock = _mk("node-b",
                                      {"node-a": "http://127.0.0.1:1"})
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        det.heartbeat("node-b")
        det.heartbeat("node-a")
        # consistent view: a owns its shards
        assert set(mgr.mapper("ds").shards_for_node("node-a")) \
            | set(mgr.mapper("ds").shards_for_node("node-b")) \
            == {0, 1, 2, 3}
        clock["t"] += 2.0           # node-a dies (heartbeat stale)
        det.heartbeat("node-b")     # we are alive
        assert poller.leader == "node-b"
        down = poller.poll_once()
        assert down == ["node-a"]
        assert sorted(mgr.mapper("ds").shards_for_node("node-b")) \
            == [0, 1, 2, 3]
        poller.stop()


class TestStickyStatuses:
    def test_stopped_not_resurrected_by_liveness(self):
        mgr, det, poller, clock = _mk("node-a", {"node-b": "http://x"})
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        shards_b = m.shards_for_node("node-b")
        assert shards_b
        target = shards_b[0]
        m.update_status(target, ShardStatus.STOPPED)
        # peer reports the shard as running: STOPPED must stick
        poller._apply_liveness("node-b", {
            "running": {"ds": [target]},
            "shards": {"ds": [{"shard": target, "status": "Active",
                               "node": "node-b"}]}})
        assert m.status(target) == ShardStatus.STOPPED
        poller.stop()

    def test_handoff_stop_does_not_mark_new_owner_stopped(self):
        """A node stopping its LOCAL ingestion because ownership moved
        must not record sticky STOPPED against the new owner — that
        would blind this node's queries to the shard forever (found by
        the 2-process cluster test: the non-leader served partial
        results after the initial shard split)."""
        from filodb_tpu.coordinator.cluster import IngestionStopped

        mgr, det, poller, clock = _mk("node-b", {"node-a": "http://x"})
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        m = mgr.mapper("ds")
        m.register_node([0], "node-a")       # ownership moved to a
        m.update_status(0, ShardStatus.ACTIVE)
        # node-b's ingest thread for shard 0 drains and reports stop
        mgr.publish_event(IngestionStopped("ds", 0, node="node-b"))
        assert m.status(0) == ShardStatus.ACTIVE   # untouched
        # but a stop from the CURRENT owner is a real stop
        mgr.publish_event(IngestionStopped("ds", 0, node="node-a"))
        assert m.status(0) == ShardStatus.STOPPED
        poller.stop()

    def test_not_running_demotes_to_assigned(self):
        mgr, det, poller, clock = _mk("node-a", {"node-b": "http://x"})
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        target = m.shards_for_node("node-b")[0]
        m.update_status(target, ShardStatus.ACTIVE)
        poller._apply_liveness("node-b", {"running": {"ds": []},
                                          "shards": {"ds": []}})
        assert m.status(target) == ShardStatus.ASSIGNED
        poller.stop()

    def test_recovery_substate_honored(self):
        mgr, det, poller, clock = _mk("node-a", {"node-b": "http://x"})
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        target = m.shards_for_node("node-b")[0]
        poller._apply_liveness("node-b", {
            "running": {"ds": [target]},
            "shards": {"ds": [{"shard": target, "status": "Recovery",
                               "node": "node-b"}]}})
        assert m.status(target) == ShardStatus.RECOVERY
        poller.stop()


class TestAdoption:
    def test_non_leader_adopts_leader_assignment(self):
        mgr, det, poller, clock = _mk("node-b", {"node-a": "http://x"})
        mgr.setup_dataset("ds", 4, min_num_nodes=2)
        # local (wrong) view: node-b owns 0,1
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        assert m.shards_for_node("node-b") == [0, 1]
        leader_view = {"shards": {"ds": [
            {"shard": 0, "status": "Active", "node": "node-a"},
            {"shard": 1, "status": "Active", "node": "node-a"},
            {"shard": 2, "status": "Assigned", "node": "node-b"},
            {"shard": 3, "status": "Assigned", "node": "node-b"},
        ]}}
        changed = poller._adopt_leader_view(leader_view)
        assert changed
        assert m.shards_for_node("node-a") == [0, 1]
        assert m.shards_for_node("node-b") == [2, 3]
        # idempotent
        assert not poller._adopt_leader_view(leader_view)
        poller.stop()
