"""Schema / record / chunk / histogram model tests."""

import numpy as np
import pytest

from filodb_tpu.core import chunk as chunkmod
from filodb_tpu.core.histogram import CustomBuckets, GeometricBuckets, Histogram, quantile_bulk
from filodb_tpu.core.record import (RecordBuilder, canonical_partkey, decode_container,
                                    parse_partkey, partition_hash, shard_key_hash)
from filodb_tpu.core.schemas import (DEFAULT_SCHEMAS, ColumnType, DatasetOptions, Schemas,
                                     DEFAULT_SCHEMA_CONFIG)

rng = np.random.default_rng(7)


class TestSchemas:
    def test_default_schemas(self):
        for name in ("gauge", "untyped", "prom-counter", "prom-histogram", "ds-gauge"):
            assert DEFAULT_SCHEMAS.get(name) is not None
        pc = DEFAULT_SCHEMAS["prom-counter"]
        assert pc.data.columns[0].ctype == ColumnType.TIMESTAMP
        assert pc.data.column("count").detect_drops
        assert DEFAULT_SCHEMAS["prom-histogram"].data.column("h").ctype == ColumnType.HISTOGRAM

    def test_hash_lookup(self):
        g = DEFAULT_SCHEMAS["gauge"]
        assert DEFAULT_SCHEMAS.by_hash(g.schema_hash) is g

    def test_downsample_schema_links(self):
        assert DEFAULT_SCHEMAS["gauge"].downsample.data.name == "ds-gauge"
        assert DEFAULT_SCHEMAS["prom-counter"].downsample is None  # self-downsampling

    def test_first_column_must_be_ts(self):
        bad = {"bad": {"columns": ["value:double", "timestamp:ts"], "value-column": "value"}}
        with pytest.raises(ValueError):
            Schemas.from_config(bad)


class TestPartKey:
    TAGS = {"_metric_": "http_req_total", "_ws_": "demo", "_ns_": "App-0", "instance": "1"}

    def test_canonical_roundtrip(self):
        pk = canonical_partkey(self.TAGS)
        assert parse_partkey(pk) == self.TAGS
        # order-insensitive
        assert canonical_partkey(dict(reversed(list(self.TAGS.items())))) == pk

    def test_shard_key_hash_ignores_non_shard_tags(self):
        opts = DatasetOptions()
        t2 = dict(self.TAGS, instance="2")
        assert shard_key_hash(self.TAGS, opts) == shard_key_hash(t2, opts)

    def test_metric_suffix_trimming(self):
        # _bucket/_count/_sum metrics hash with their base metric
        opts = DatasetOptions()
        base = dict(self.TAGS, _metric_="latency")
        bucket = dict(self.TAGS, _metric_="latency_bucket")
        assert shard_key_hash(base, opts) == shard_key_hash(bucket, opts)

    def test_partition_hash_ignores_le(self):
        opts = DatasetOptions()
        with_le = dict(self.TAGS, le="0.5")
        assert partition_hash(with_le, opts) == partition_hash(self.TAGS, opts)
        t2 = dict(self.TAGS, instance="2")
        assert partition_hash(t2, opts) != partition_hash(self.TAGS, opts)


class TestRecords:
    def test_container_roundtrip(self):
        schema = DEFAULT_SCHEMAS["gauge"]
        b = RecordBuilder(schema)
        for i in range(100):
            b.add(1000 + i * 10, (float(i),), {"_metric_": "m", "_ns_": "ns", "_ws_": "ws",
                                               "pod": f"p{i % 5}"})
        recs = []
        for c in b.containers():
            recs.extend(decode_container(c, DEFAULT_SCHEMAS))
        assert len(recs) == 100
        assert recs[7].timestamp == 1070
        assert recs[7].values == (7.0,)
        assert recs[7].tags["pod"] == "p2"
        assert recs[7].schema_hash == schema.schema_hash
        assert recs[7].shard_hash == shard_key_hash(recs[7].tags, DatasetOptions())

    def test_container_size_splitting(self):
        schema = DEFAULT_SCHEMAS["gauge"]
        b = RecordBuilder(schema, container_size=1024)
        for i in range(200):
            b.add(i, (1.0,), {"_metric_": "m", "tag": "v" * 50})
        cs = b.containers()
        assert len(cs) > 1
        total = sum(len(list(decode_container(c, DEFAULT_SCHEMAS))) for c in cs)
        assert total == 200

    def test_histogram_record(self):
        schema = DEFAULT_SCHEMAS["prom-histogram"]
        from filodb_tpu.codecs import histcodec
        buckets = GeometricBuckets(2.0, 2.0, 8)
        hist_blob = histcodec.encode(buckets, np.arange(8, dtype=np.int64)[None, :])
        b = RecordBuilder(schema)
        b.add(5000, (1.5, 10.0, hist_blob), {"_metric_": "lat"})
        recs = list(decode_container(b.containers()[0], DEFAULT_SCHEMAS))
        assert recs[0].values[0] == 1.5
        _, rows = histcodec.decode(recs[0].values[2])
        assert np.array_equal(rows[0], np.arange(8))


class TestChunks:
    def test_chunkset_roundtrip_gauge(self):
        schema = DEFAULT_SCHEMAS["gauge"]
        ts = np.arange(0, 300 * 10_000, 10_000, dtype=np.int64)
        vals = rng.normal(50, 10, 300)
        cs = chunkmod.encode_chunkset(schema, b"pk", ts, [vals])
        assert cs.info.num_rows == 300
        assert cs.info.start_time == 0 and cs.info.end_time == ts[-1]
        ts2, (vals2,) = chunkmod.decode_chunkset(schema, cs)
        assert np.array_equal(ts2, ts)
        assert np.array_equal(vals2, vals)

    def test_chunkset_histogram(self):
        schema = DEFAULT_SCHEMAS["prom-histogram"]
        buckets = GeometricBuckets(2.0, 2.0, 8)
        n = 50
        ts = np.arange(n, dtype=np.int64) * 1000
        sums = np.cumsum(rng.random(n))
        counts = np.arange(n, dtype=np.float64)
        rows = np.cumsum(np.cumsum(rng.integers(0, 3, (n, 8)), axis=1), axis=0)
        cs = chunkmod.encode_chunkset(schema, b"pk", ts, [sums, counts, (buckets, rows)])
        ts2, cols = chunkmod.decode_chunkset(schema, cs)
        assert np.array_equal(cols[0], sums)
        b2, rows2 = cols[2]
        assert np.array_equal(rows2, rows)

    def test_build_batch_padding(self):
        ts_list = [np.arange(5, dtype=np.int64), np.arange(9, dtype=np.int64)]
        val_list = [np.ones(5), np.ones(9)]
        batch = chunkmod.build_batch(ts_list, val_list, pad_to=8)
        assert batch.timestamps.shape == (2, 16)
        assert batch.timestamps[0, 5] == chunkmod.TS_PAD
        assert np.isnan(batch.values[0, 5])
        assert batch.row_counts.tolist() == [5, 9]

    def test_chunk_id_ordering(self):
        assert chunkmod.chunk_id(1000) < chunkmod.chunk_id(2000)
        assert chunkmod.chunk_id(1000, 1) > chunkmod.chunk_id(1000, 0)


class TestHistogramModel:
    def test_quantile_interpolation(self):
        buckets = CustomBuckets(np.array([1.0, 2.0, 4.0, np.inf]))
        h = Histogram(buckets, np.array([0.0, 10.0, 10.0, 10.0]))
        # all 10 observations in (1,2] -> median interpolates inside bucket 1
        assert h.quantile(0.5) == pytest.approx(1.5)
        # out-of-range q (reference: Histogram.quantile)
        assert h.quantile(-0.1) == -np.inf
        assert h.quantile(1.1) == np.inf

    def test_quantile_inf_bucket(self):
        buckets = CustomBuckets(np.array([1.0, 2.0, np.inf]))
        h = Histogram(buckets, np.array([0.0, 0.0, 10.0]))
        # everything in +Inf bucket -> second-to-last bucket top
        assert h.quantile(0.99) == pytest.approx(2.0)

    def test_quantile_bulk_matches_scalar(self):
        tops = np.array([0.5, 1, 2.5, 5, 10, np.inf])
        rows = np.cumsum(rng.integers(0, 5, (30, 6)), axis=1).astype(float)
        bulk = quantile_bulk(tops, rows, 0.9)
        buckets = CustomBuckets(tops)
        for i in range(30):
            assert bulk[i] == pytest.approx(Histogram(buckets, rows[i]).quantile(0.9), nan_ok=True)

    def test_add_schema_mismatch(self):
        h1 = Histogram(GeometricBuckets(1, 2, 4), np.ones(4))
        h2 = Histogram(GeometricBuckets(1, 3, 4), np.ones(4))
        with pytest.raises(ValueError):
            h1 + h2

    def test_geometric_1(self):
        b = GeometricBuckets(2.0, 2.0, 3, starts_at_one=True)
        assert b.bucket_tops().tolist() == [1.0, 2.0, 4.0, 8.0]


class TestReviewRegressions:
    def test_int_column_negative_values(self):
        from filodb_tpu.core.schemas import Schemas
        sc = Schemas.from_config({"ev": {"columns": ["timestamp:ts", "code:int"],
                                         "value-column": "code"}})
        s = sc["ev"]
        ts = np.arange(4, dtype=np.int64)
        cs = chunkmod.encode_chunkset(s, b"pk", ts, [np.array([-5, 3, -1, 7])])
        _, (codes,) = chunkmod.decode_chunkset(s, cs)
        assert codes.tolist() == [-5, 3, -1, 7]

    def test_encode_chunkset_validates_lengths(self):
        schema = DEFAULT_SCHEMAS["gauge"]
        ts = np.arange(10, dtype=np.int64)
        with pytest.raises(ValueError):
            chunkmod.encode_chunkset(schema, b"pk", ts, [np.ones(6)])
        with pytest.raises(ValueError):
            chunkmod.encode_chunkset(schema, b"pk", ts, [])

    def test_quantile_bulk_nan_rows_stay_nan(self):
        tops = np.array([-1.0, 2.0, np.inf])
        rows = np.array([[np.nan, np.nan, np.nan], [1.0, 2.0, 3.0]])
        out = quantile_bulk(tops, rows, 0.5)
        assert np.isnan(out[0]) and np.isfinite(out[1])
