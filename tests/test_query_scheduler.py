"""Query admission/scheduling (reference: QueryActor.scala:28-40
priority mailbox by submitTime, :112-131 dedicated query scheduler)."""

import threading
import time

import pytest

from filodb_tpu.query.model import QueryError
from filodb_tpu.query.scheduler import QueryRejected, QueryScheduler


def _mk(**kw):
    kw.setdefault("num_workers", 1)
    kw.setdefault("max_queued", 8)
    return QueryScheduler(**kw)


class TestScheduling:
    def test_executes_and_returns(self):
        s = _mk()
        try:
            assert s.execute(lambda: 41 + 1) == 42
        finally:
            s.shutdown()

    def test_oldest_submit_time_runs_first(self):
        s = _mk()
        try:
            gate = threading.Event()
            started = threading.Event()
            order = []
            # occupy the single worker so submissions queue up
            blocker = s.submit(lambda: started.set() or gate.wait(5))
            started.wait(5)
            futs = []
            for st, tag in ((3000, "newest"), (1000, "oldest"),
                            (2000, "middle")):
                futs.append(s.submit(
                    lambda t=tag: order.append(t) or t, submit_time_ms=st))
            gate.set()
            for f in futs:
                f.result(timeout=5)
            blocker.result(timeout=5)
            assert order == ["oldest", "middle", "newest"]
        finally:
            s.shutdown()

    def test_equal_submit_time_is_fifo(self):
        s = _mk()
        try:
            gate = threading.Event()
            started = threading.Event()
            order = []
            s.submit(lambda: started.set() or gate.wait(5))
            started.wait(5)
            futs = [s.submit(lambda i=i: order.append(i), submit_time_ms=7)
                    for i in range(5)]
            gate.set()
            for f in futs:
                f.result(timeout=5)
            assert order == [0, 1, 2, 3, 4]
        finally:
            s.shutdown()


class TestAdmission:
    def test_full_queue_rejects(self):
        s = _mk(max_queued=2)
        try:
            gate = threading.Event()
            started = threading.Event()

            def blocker():
                started.set()
                gate.wait(5)

            s.submit(blocker)
            started.wait(5)                    # worker busy for sure
            s.submit(lambda: 1)                # queued
            s.submit(lambda: 2)                # queued (full now)
            with pytest.raises(QueryRejected):
                s.submit(lambda: 3)
            gate.set()
        finally:
            s.shutdown()

    def test_overdue_queued_query_fails_without_running(self):
        s = _mk()
        try:
            gate = threading.Event()
            started = threading.Event()
            ran = []
            s.submit(lambda: started.set() or gate.wait(5))
            started.wait(5)
            fut = s.submit(lambda: ran.append(1), timeout_ms=30)
            time.sleep(0.1)                    # let it go overdue in queue
            gate.set()
            with pytest.raises(QueryError, match="in queue"):
                fut.result(timeout=5)
            assert not ran
        finally:
            s.shutdown()

    def test_execute_timeout(self):
        s = _mk()
        try:
            with pytest.raises(QueryError, match="timed out"):
                s.execute(lambda: time.sleep(2), timeout_ms=100)
        finally:
            s.shutdown()

    def test_shutdown_fails_queued_and_rejects_new(self):
        s = _mk()
        gate = threading.Event()
        s.submit(lambda: gate.wait(5))
        queued = s.submit(lambda: 1)
        gate.set()
        s.shutdown(wait=False)
        with pytest.raises(QueryRejected):
            s.submit(lambda: 2)
        with pytest.raises((QueryRejected, Exception)):
            queued.result(timeout=5)

    def test_worker_exception_propagates(self):
        s = _mk()
        try:
            def boom():
                raise RuntimeError("kernel error")
            with pytest.raises(RuntimeError, match="kernel error"):
                s.execute(boom)
            # scheduler still healthy afterwards
            assert s.execute(lambda: 7) == 7
        finally:
            s.shutdown()


class TestHttpIntegration:
    def test_server_routes_queries_through_scheduler(self):
        import json
        import urllib.request

        from filodb_tpu.standalone import FiloServer

        srv = FiloServer({"node": "qs", "datasets": [
            {"name": "prom", "num-shards": 1, "schema": "gauge",
             "query": {"workers": 2, "max-queued": 4}}]})
        port = srv.start()
        try:
            sched = srv.query_schedulers["prom"]
            before = None
            import urllib.parse
            qs = urllib.parse.urlencode({
                "query": "up", "start": 1_700_000_000,
                "end": 1_700_000_060, "step": "15s"})
            try:
                body = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/promql/prom/api/v1/"
                    f"query_range?{qs}", timeout=30).read())
            except urllib.error.HTTPError as e:
                raise AssertionError(
                    f"HTTP {e.code}: {e.read().decode()[:500]}") from e
            assert body["status"] == "success"
            from filodb_tpu.utils.observability import REGISTRY
            done = REGISTRY.counter("filodb_queries_executed_total")
            assert done.value(scheduler="query-prom") >= 1
        finally:
            srv.shutdown()

    def test_overload_returns_503(self):
        import urllib.error
        import urllib.parse
        import urllib.request

        from filodb_tpu.standalone import FiloServer

        srv = FiloServer({"node": "qs2", "datasets": [
            {"name": "prom", "num-shards": 1, "schema": "gauge",
             "query": {"workers": 1, "max-queued": 1}}]})
        port = srv.start()
        try:
            sched = srv.query_schedulers["prom"]
            gate = threading.Event()
            started = threading.Event()

            def blocker():
                started.set()
                gate.wait(10)

            sched.submit(blocker)                 # occupy the worker
            started.wait(5)
            sched.submit(lambda: 1)               # fill the queue
            qs = urllib.parse.urlencode({
                "query": "up", "start": 1_700_000_000,
                "end": 1_700_000_060, "step": "15s"})
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/promql/prom/api/v1/"
                    f"query_range?{qs}", timeout=30)
            assert exc.value.code == 503
            gate.set()
        finally:
            srv.shutdown()
