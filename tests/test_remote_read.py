"""Prometheus remote-storage protocol: snappy block codec, prompb wire
codec, and the /api/v1/read + /api/v1/write HTTP endpoints.

Reference being matched: prometheus/src/main/proto/remote-storage.proto
(wire contract), PrometheusModel.scala:12 conversions,
PrometheusApiRoute.scala:38-60 /read route.
"""

import json
import urllib.request

import numpy as np
import pytest

from filodb_tpu.http import remote as pb
from filodb_tpu.utils import snappy

BASE = 1_700_000_000_000


class TestSnappy:
    @pytest.mark.parametrize("data", [
        b"", b"a", b"abc", b"hello world " * 100,
        bytes(range(256)) * 40, b"\x00" * 10_000,
        b"abcd" * 3 + b"xyz",
    ])
    def test_roundtrip(self, data):
        comp = snappy.compress(data)
        assert snappy.decompress(comp) == data

    def test_random_roundtrip(self):
        rng = np.random.default_rng(0)
        for _ in range(20):
            n = int(rng.integers(0, 5000))
            # mix of random and repetitive content
            data = bytes(rng.integers(0, 4, n, dtype=np.uint8))
            comp = snappy.compress(data)
            assert snappy.decompress(comp) == data

    def test_compresses_repetitive_data(self):
        data = (b'{"__name__":"http_requests_total","job":"api"}' * 200)
        comp = snappy.compress(data)
        assert len(comp) < len(data) // 4

    def test_decompress_reference_vectors(self):
        # hand-built snappy streams: literal + copy elements
        # "abcdabcd": literal "abcd" then copy2(off=4, len=4)
        stream = bytes([8]) + bytes([3 << 2]) + b"abcd" \
            + bytes([(3 << 2) | 2]) + (4).to_bytes(2, "little")
        assert snappy.decompress(stream) == b"abcdabcd"
        # RLE via overlapping copy: literal "a" + copy1(off=1, len=7)
        stream = bytes([8]) + bytes([0]) + b"a" \
            + bytes([((0) << 5) | ((7 - 4) << 2) | 1, 1])
        assert snappy.decompress(stream) == b"a" * 8

    def test_corrupt_inputs_raise(self):
        with pytest.raises(ValueError):
            snappy.decompress(b"")
        with pytest.raises(ValueError):
            snappy.decompress(bytes([10, 3 << 2]) + b"ab")  # short literal
        with pytest.raises(ValueError):  # copy before any output
            snappy.decompress(bytes([4, (3 << 2) | 2]) +
                              (9).to_bytes(2, "little"))


class TestPromPb:
    def test_read_request_roundtrip(self):
        q = pb.RemoteQuery(BASE, BASE + 60_000, [
            pb.LabelMatcher(pb.MATCH_EQUAL, "__name__", "up"),
            pb.LabelMatcher(pb.MATCH_REGEX, "job", "api|web"),
            pb.LabelMatcher(pb.MATCH_NOT_EQUAL, "env", "dev"),
        ])
        buf = pb.encode_read_request([q])
        back = pb.decode_read_request(buf)
        assert len(back) == 1
        assert back[0].start_ms == BASE and back[0].end_ms == BASE + 60_000
        assert [(m.type, m.name, m.value) for m in back[0].matchers] == \
            [(0, "__name__", "up"), (2, "job", "api|web"),
             (1, "env", "dev")]

    def test_time_series_roundtrip(self):
        labels = {"__name__": "up", "job": "api"}
        ts = [BASE, BASE + 1000, BASE + 2000]
        vals = [1.0, 0.0, 1.5]
        blob = pb.encode_time_series(labels, ts, vals)
        resp = pb.encode_read_response([[blob]])
        back = pb.decode_read_response(resp)
        assert len(back) == 1 and len(back[0]) == 1
        lb, t2, v2 = back[0][0]
        assert lb == labels and t2 == ts and v2 == vals

    def test_negative_timestamp_int64(self):
        blob = pb.encode_time_series({}, [-5], [2.0])
        resp = pb.encode_read_response([[blob]])
        _, t2, v2 = pb.decode_read_response(resp)[0][0]
        assert t2 == [-5] and v2 == [2.0]

    def test_write_request_roundtrip(self):
        series = [({"__name__": "m", "i": "0"}, [BASE], [3.5]),
                  ({"__name__": "m", "i": "1"}, [BASE, BASE + 500],
                   [1.0, 2.0])]
        buf = pb.encode_write_request(series)
        back = pb.decode_write_request(buf)
        assert [(lb, list(t), list(v)) for lb, t, v in back] == \
            [(lb, list(t), list(v)) for lb, t, v in series]

    def test_matchers_to_filters(self):
        fs = pb.matchers_to_filters([
            pb.LabelMatcher(pb.MATCH_EQUAL, "__name__", "up"),
            pb.LabelMatcher(pb.MATCH_NOT_REGEX, "job", "a.*")],
            metric_column="_metric_")
        assert fs[0].column == "_metric_"
        assert fs[0].matches({"_metric_": "up"})
        assert not fs[1].matches({"job": "abc"})
        assert fs[1].matches({"job": "zzz"})


@pytest.fixture(scope="module")
def server():
    from filodb_tpu.standalone import FiloServer
    config = {
        "node": "rr-node",
        "datasets": [{"name": "prom", "num-shards": 2, "schema": "gauge",
                      "spread": 1, "store": {"groups-per-shard": 2}}],
    }
    srv = FiloServer(config)
    port = srv.start()
    # ingest directly via the write_router-backed remote-write endpoint
    yield srv, port
    srv.shutdown()


def _post(port, path, payload: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=payload,
        headers={"Content-Type": "application/x-protobuf",
                 "Content-Encoding": "snappy"})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestRemoteEndpoints:
    def test_write_then_read(self, server):
        srv, port = server
        series = []
        for i in range(4):
            labels = {"__name__": "rr_metric", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"}
            ts = [BASE + k * 10_000 for k in range(30)]
            vals = [float(i * 100 + k) for k in range(30)]
            series.append((labels, ts, vals))
        code, body = _post(port, "/promql/prom/api/v1/write",
                           snappy.compress(pb.encode_write_request(series)))
        assert code == 200, body
        assert json.loads(body)["samples"] == 120

        # ingestion is async through the stream; wait for arrival
        import time
        deadline = time.time() + 10
        while time.time() < deadline:
            rows = sum(sh.stats.rows_ingested
                       for sh in srv.memstore.shards("prom"))
            if rows >= 120:
                break
            time.sleep(0.05)
        assert rows == 120

        q = pb.RemoteQuery(BASE, BASE + 300_000, [
            pb.LabelMatcher(pb.MATCH_EQUAL, "__name__", "rr_metric"),
            pb.LabelMatcher(pb.MATCH_EQUAL, "_ws_", "w"),
            pb.LabelMatcher(pb.MATCH_EQUAL, "_ns_", "n")])
        code, body = _post(port, "/promql/prom/api/v1/read",
                           snappy.compress(pb.encode_read_request([q])))
        assert code == 200, body
        results = pb.decode_read_response(snappy.decompress(body))
        assert len(results) == 1
        got = {lb["inst"]: (t, v) for lb, t, v in results[0]}
        assert set(got) == {f"i{i}" for i in range(4)}
        for labels, ts, vals in series:
            t2, v2 = got[labels["inst"]]
            assert t2 == ts and v2 == vals
        # labels carry __name__, not the internal metric column
        assert all(lb.get("__name__") == "rr_metric"
                   for lb, _, _ in results[0])

    def test_read_regex_and_range_clamp(self, server):
        srv, port = server
        q = pb.RemoteQuery(BASE + 100_000, BASE + 150_000, [
            pb.LabelMatcher(pb.MATCH_EQUAL, "__name__", "rr_metric"),
            pb.LabelMatcher(pb.MATCH_REGEX, "inst", "i[01]")])
        code, body = _post(port, "/promql/prom/api/v1/read",
                           snappy.compress(pb.encode_read_request([q])))
        assert code == 200
        results = pb.decode_read_response(snappy.decompress(body))
        assert {lb["inst"] for lb, _, _ in results[0]} == {"i0", "i1"}
        for _, ts, _ in results[0]:
            assert all(BASE + 100_000 <= t <= BASE + 150_000 for t in ts)

    def test_unknown_dataset_404(self, server):
        _, port = server
        code, _ = _post(port, "/promql/nope/api/v1/read",
                        snappy.compress(pb.encode_read_request([])))
        assert code == 404

    def test_garbage_payload_400(self, server):
        _, port = server
        code, _ = _post(port, "/promql/prom/api/v1/read", b"\xff\xfe")
        assert code == 400
