"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against `--xla_force_host_platform_device_count=8` (the stand-in for the
reference's sbt-multi-jvm cluster tests, SURVEY.md §4).
"""

import os

# The environment pre-sets JAX_PLATFORMS=axon,cpu (the real TPU tunnel), so
# this must be a hard override, not a setdefault — tests need the virtual
# CPU mesh and exact (non-emulated) float64.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
