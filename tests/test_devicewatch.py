"""Device-resource observability (ISSUE 4): HBM residency ledger,
JIT compile telemetry + recompile-storm detection, flight recorder,
and the /admin/device | /admin/flightrecorder | /admin/config routes.

The load-bearing invariant is LEDGER RECONCILIATION: at any quiescent
point, the ledger's per-owner byte totals must equal the sum of
``nbytes`` over the device arrays actually held by the caches it
accounts for — across block commit, repeat-query reuse,
overflow-eviction, epoch purges, and ODP page-in/out churn.  A drifting
ledger is worse than none (operators size HBM budgets from it).
"""

import collections
import gc
import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.logical import RangeFunctionId as F
from filodb_tpu.utils import devicewatch
from filodb_tpu.utils.devicewatch import (COMPILE_WATCH, FLIGHT,
                                          KERNEL_TIMER, LEDGER,
                                          CompileWatch, FlightRecorder,
                                          KernelTimer, device_metrics)

STEP = 60_000
T0 = 1_700_000_040_000
WINDOW = 300_000
K = WINDOW // STEP


def _mk_shard(dataset, n_series=6, n_rows=50, seed=0, ms=None, **cfg_kw):
    """Regular (one sample per bucket) series so the device grid serves."""
    ms = ms or TimeSeriesMemStore()
    cfg = StoreConfig(**cfg_kw)
    shard = ms.setup(dataset, DEFAULT_SCHEMAS, 0, cfg)
    rng = np.random.default_rng(seed)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(n_series):
        tags = {"__name__": "req_total", "instance": f"i{i}", "_ws_": "w",
                "_ns_": "n"}
        ts = T0 + np.arange(n_rows, dtype=np.int64) * STEP
        vals = np.cumsum(rng.random(n_rows) * 5)
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    shard.flush_all()
    return ms, shard


def _ids(shard, metric="req_total"):
    return shard.lookup_partitions(
        [ColumnFilter("_metric_", Equals(metric))], 0, 2**62).part_ids


def _expected_grid_bytes(cache) -> dict:
    """Walk a DeviceGridCache's resident device arrays: what the ledger
    MUST show for this owner, by format."""
    by_fmt: collections.Counter = collections.Counter()
    blocks = list(cache.blocks.values()) \
        + [blk for _v, blk in cache._tails.values()]
    for blk in blocks:
        if blk.ts is not None:
            by_fmt["dense"] += int(blk.ts.nbytes)
        elif blk.ts_desc is not None:
            by_fmt["compressed"] += int(blk.ts_desc["phase"].nbytes)
        if isinstance(blk.vals, dict):
            by_fmt["compressed"] += sum(int(a.nbytes)
                                        for a in blk.vals.values())
        else:
            by_fmt["dense"] += int(blk.vals.nbytes)
    for _host, dev in cache._phase_memo.values():
        by_fmt["scratch"] += int(dev.nbytes)
    for memo in cache._mesh_stage_memo.values():
        _pid, ts_st, val_st = memo[0], memo[1], memo[2]
        if ts_st is not None:
            by_fmt["mesh-staged"] += int(ts_st.nbytes)
        by_fmt["mesh-staged"] += int(val_st.nbytes)
    return dict(by_fmt)


def _assert_reconciled(cache):
    """Ledger per-format totals == walked device-array bytes, exactly."""
    gc.collect()   # run finalizers of any just-dropped arrays
    got = {fmt: row["bytes"]
           for fmt, row in LEDGER.owners().get(cache.owner, {}).items()
           if row["bytes"]}
    want = {fmt: n for fmt, n in _expected_grid_bytes(cache).items() if n}
    assert got == want, f"ledger drift for {cache.owner}: " \
                        f"ledger={got} actual={want}"


def _grid_cache(shard):
    caches = list(shard.device_caches.values())
    assert caches, "grid never built"
    return caches[0]


# ---------------------------------------------------------------------------
# ledger unit behavior
# ---------------------------------------------------------------------------


class TestLedger:
    def test_track_and_release_on_gc(self):
        owner = "test:unit-release"
        a = LEDGER.device_put(np.zeros(1024, np.float32), owner=owner,
                              fmt="dense")
        assert LEDGER.owners()[owner]["dense"]["bytes"] == a.nbytes
        hw = LEDGER.owners()[owner]["dense"]["high_watermark"]
        assert hw == a.nbytes
        del a
        gc.collect()
        assert LEDGER.owners()[owner]["dense"]["bytes"] == 0
        # the watermark survives the release (peak sizing signal)
        assert LEDGER.owners()[owner]["dense"]["high_watermark"] == hw

    def test_noop_put_is_not_double_counted(self):
        owner = "test:unit-noop"
        a = LEDGER.device_put(np.zeros(256, np.int32), owner=owner,
                              fmt="dense")
        b = LEDGER.device_put(a, owner="test:unit-noop-other", fmt="dense")
        assert b is a                      # jax no-op put
        assert "test:unit-noop-other" not in LEDGER.owners()
        assert LEDGER.owners()[owner]["dense"]["bytes"] == a.nbytes
        LEDGER.track(a, owner=owner, fmt="dense")   # idempotent re-track
        assert LEDGER.owners()[owner]["dense"]["bytes"] == a.nbytes

    def test_eviction_attribution(self):
        c0 = device_metrics()["evictions"].value(owner="test:unit-evict",
                                                 reason="budget_overflow")
        LEDGER.note_eviction("test:unit-evict", "budget_overflow", n=3,
                             nbytes=123)
        assert device_metrics()["evictions"].value(
            owner="test:unit-evict", reason="budget_overflow") == c0 + 3
        kinds = [e for e in FLIGHT.events(kind="evict")
                 if e.get("owner") == "test:unit-evict"]
        assert kinds and kinds[-1]["bytes"] == 123

    def test_disabled_wrapper_is_passthrough(self):
        devicewatch.set_enabled(False)
        try:
            a = LEDGER.device_put(np.zeros(64), owner="test:unit-off",
                                  fmt="dense")
            assert "test:unit-off" not in LEDGER.owners()
            assert np.asarray(a).shape == (64,)
        finally:
            devicewatch.set_enabled(True)


# ---------------------------------------------------------------------------
# compile telemetry + storm detector
# ---------------------------------------------------------------------------


class TestCompileWatch:
    def test_jit_counts_compiles_per_shape(self):
        calls = {"n": 0}

        def f(x):
            calls["n"] += 1
            return x * 2

        prog = "test.unit_jit"
        wrapped = devicewatch.jit(f, program=prog)
        m = device_metrics()["jit_compiles"]
        c0 = m.value(program=prog)
        np.testing.assert_allclose(wrapped(np.ones(4, np.float32)),
                                   np.full(4, 2.0, np.float32))
        wrapped(np.ones(4, np.float32))           # cached: no new compile
        assert m.value(program=prog) == c0 + 1
        wrapped(np.ones(8, np.float32))           # new shape: compiles
        assert m.value(program=prog) == c0 + 2
        rows = [r for r in COMPILE_WATCH.table() if r["program"] == prog]
        assert rows and rows[0]["compiles"] >= 2
        assert "float32[4]" in ";".join(rows[0]["last_shape_key"]
                                        for r in rows) \
            or "float32[8]" in rows[0]["last_shape_key"]

    def test_storm_detector_fires_on_shape_cycling(self):
        cw = CompileWatch(storm_shapes=4, storm_window_s=300.0)
        prog = "test.unit_storm"
        for i in range(4):
            cw.note_compile(prog, 0.01, f"float32[{i}]")
        assert prog in cw.active_storms()
        row = [r for r in cw.table() if r["program"] == prog][0]
        assert row["storms"] == 1 and row["distinct_shapes"] == 4
        # one storm per window, not one per compile
        cw.note_compile(prog, 0.01, "float32[99]")
        assert [r for r in cw.table()
                if r["program"] == prog][0]["storms"] == 1

    def test_grid_query_shape_cycling_trips_the_detector(self):
        """E2E: a dashboard leaking nsteps into the program signature is
        THE storm the detector exists for — cycle query shapes through
        the device grid and watch it fire."""
        ms, shard = _mk_shard("dw_storm")
        ids = _ids(shard)
        old = (COMPILE_WATCH.storm_shapes, COMPILE_WATCH.storm_window_s)
        COMPILE_WATCH.configure(storm_shapes=4, storm_window_s=600.0)
        try:
            steps0 = T0 + (K - 1) * STEP
            served = 0
            for nsteps in range(40, 45):          # 5 distinct shapes
                got = shard.scan_grid(ids, F.RATE, steps0, nsteps, STEP,
                                      WINDOW)
                served += got is not None
            assert served == 5, "grid fast path did not serve"
            storms = COMPILE_WATCH.active_storms()
            assert any(p.startswith(("devicestore.", "grid."))
                       for p in storms), storms
            assert any(e["kind"] == "jit.storm"
                       for e in FLIGHT.events(kind="jit.storm"))
        finally:
            COMPILE_WATCH.configure(storm_shapes=old[0],
                                    storm_window_s=old[1])


# ---------------------------------------------------------------------------
# kernel flight deck: sampled device-time ledger + regression sentry
# (ISSUE 15)
# ---------------------------------------------------------------------------


def _kt_row(program):
    rows = [r for r in KERNEL_TIMER.table() if r["program"] == program]
    return rows[0] if rows else None


@pytest.fixture()
def kt_config():
    """Snapshot + restore the process-wide KernelTimer knobs so tests
    can crank the sample rate / sentry windows without leaking."""
    kt = KERNEL_TIMER
    old = (kt.sample_1_in, kt.hbm_roof_bytes_per_s, kt.regression_factor,
           kt.regression_window_s, kt.baseline_min_samples)
    yield kt
    kt.configure(sample_1_in=old[0], hbm_roof_bytes_per_s=old[1],
                 regression_factor=old[2], regression_window_s=old[3],
                 baseline_min_samples=old[4])


class TestKernelTimer:
    def test_every_launch_counts_and_1_in_n_samples(self, kt_config):
        kt_config.configure(sample_1_in=4)
        prog = "test.kt_count"
        f = devicewatch.jit(lambda x: x + 1, program=prog)
        for _ in range(9):
            f(np.ones(4, np.float32))
        row = _kt_row(prog)
        assert row["launches"] == 9
        # sampled launches are 1, 5, 9; launch 1 compiled (a compiling
        # launch is host trace time, never folded) -> 2 folded samples
        assert row["sampled"] == 2
        assert row["ewma_device_s"] is not None
        assert row["device_seconds"] > 0
        assert sum(row["seconds_histogram"].values()) == 2
        assert device_metrics()["kernel_launches"].value(
            program=prog) == row["launches"]
        assert device_metrics()["kernel_seconds"].value(
            program=prog) == pytest.approx(row["device_seconds"],
                                           abs=1e-6)

    def test_sample_rate_zero_disables_sampling_not_counting(self,
                                                             kt_config):
        kt_config.configure(sample_1_in=0)
        prog = "test.kt_off"
        f = devicewatch.jit(lambda x: x * 2, program=prog)
        for _ in range(5):
            f(np.ones(4, np.float32))
        row = _kt_row(prog)
        assert row["launches"] == 5 and row["sampled"] == 0
        assert device_metrics()["kernel_launches"].value(program=prog) == 5

    def test_disabled_devicewatch_is_passthrough(self, kt_config):
        kt_config.configure(sample_1_in=1)
        prog = "test.kt_killswitch"
        f = devicewatch.jit(lambda x: x - 1, program=prog)
        f(np.ones(4, np.float32))          # compile while enabled
        devicewatch.set_enabled(False)
        try:
            f(np.ones(4, np.float32))
            # bytes notes freeze with the switch too — accumulating
            # against a frozen launch count would permanently inflate
            # achieved-bytes/s after a disable/enable cycle
            KERNEL_TIMER.note_bytes(prog, 4096)
        finally:
            devicewatch.set_enabled(True)
        row = _kt_row(prog)
        assert row["launches"] == 1   # the disabled launch is
        # invisible everywhere (same contract as the ledger/compile
        # wrappers): counting resumes with the switch
        assert row["bytes_total"] == 0

    def test_bytes_join_yields_roofline_fraction(self, kt_config):
        kt = KernelTimer(sample_1_in=1, hbm_roof_bytes_per_s=1e9,
                         baseline_min_samples=100)
        kt.note_bytes("p", 4_000)
        kt._fold("p", 0.001, "k")          # 4000 B / launch... but
        # launches=0 until tick(); note_bytes alone must not divide by 0
        row = [r for r in kt.table() if r["program"] == "p"][0]
        assert row["roofline_fraction"] is None
        assert kt.tick("p")
        kt._fold("p", 0.001, "k")
        row = [r for r in kt.table() if r["program"] == "p"][0]
        # 4000 bytes / 1 launch / ewma(0.001 s) / roof(1e9 B/s)
        assert row["achieved_bytes_per_s"] == pytest.approx(4e6, rel=0.01)
        assert row["roofline_fraction"] == pytest.approx(4e-3, rel=0.01)

    def test_baseline_store_merge_and_persist(self, kt_config):
        saved = {}
        kt = KernelTimer(sample_1_in=1, baseline_min_samples=2)
        kt.attach_baseline_store(
            load_fn=lambda: {"p": 0.001},
            save_fn=lambda prog, s: saved.__setitem__(prog, s))
        # learned EWMA above the persisted floor: the floor wins
        kt._fold("p", 0.004, "k")
        kt._fold("p", 0.004, "k")
        row = [r for r in kt.table() if r["program"] == "p"][0]
        assert row["baseline_s"] == pytest.approx(0.001)
        # a genuine improvement ratchets down AND persists (>=5% better)
        for _ in range(40):
            kt._fold("p", 0.0001, "k")
        row = [r for r in kt.table() if r["program"] == "p"][0]
        assert row["baseline_s"] < 0.001
        # persistence is rate-limited to >=5% improvements, so the
        # stored floor may lag the live baseline by up to that margin
        assert saved and saved["p"] == pytest.approx(row["baseline_s"],
                                                     rel=0.06)

    def test_regression_sentry_episode_lifecycle(self, kt_config):
        """The ISSUE 15 chaos contract: an injected sustained slowdown
        fires EXACTLY one kernel.regression episode; recovery re-arms;
        a second slowdown is a second episode."""
        from filodb_tpu.integrity.faultinject import (
            clear_kernel_slowdown, inject_kernel_slowdown)
        kt_config.configure(sample_1_in=1, baseline_min_samples=4,
                            regression_window_s=0.1,
                            regression_factor=1.5)
        prog = "test.kt_sentry"
        f = devicewatch.jit(lambda x: x * 3, program=prog)
        arr = np.ones(8, np.float32)
        for _ in range(8):
            f(arr)
        row = _kt_row(prog)
        assert row["baseline_s"] is not None and not row["regressed"]
        m = device_metrics()
        assert m["kernel_regressions"].value(program=prog) == 0
        assert m["kernel_regressed"].value(program=prog) == 0.0

        def regression_events():
            return [e for e in FLIGHT.events(kind="kernel.regression")
                    if e.get("program") == prog]

        inject_kernel_slowdown(prog, 0.02)
        try:
            for _ in range(60):
                f(arr)
                if _kt_row(prog)["regressed"]:
                    break
            row = _kt_row(prog)
            assert row["regressed"] and row["episodes"] == 1
            assert len(regression_events()) == 1
            assert m["kernel_regressions"].value(program=prog) == 1
            assert m["kernel_regressed"].value(program=prog) == 1.0
            # sustained slowness does NOT re-fire within the episode
            for _ in range(10):
                f(arr)
            assert len(regression_events()) == 1
            assert m["kernel_regressions"].value(program=prog) == 1
        finally:
            clear_kernel_slowdown(prog)
        for _ in range(100):
            f(arr)
            if not _kt_row(prog)["regressed"]:
                break
        assert not _kt_row(prog)["regressed"]
        assert m["kernel_regressed"].value(program=prog) == 0.0
        assert any(e.get("program") == prog
                   for e in FLIGHT.events(kind="kernel.recovery"))
        # re-armed: a second slowdown opens a SECOND episode
        inject_kernel_slowdown(prog, 0.02)
        try:
            for _ in range(60):
                f(arr)
                if _kt_row(prog)["regressed"]:
                    break
            assert _kt_row(prog)["episodes"] == 2
            assert len(regression_events()) == 2
        finally:
            clear_kernel_slowdown(prog)
        for _ in range(100):
            f(arr)
            if not _kt_row(prog)["regressed"]:
                break

    def test_loaded_baseline_survives_a_cold_fast_sample(self,
                                                         kt_config):
        """Review fix: a restart resets the EWMA, so the FIRST sample
        (ew = dt exactly) of a mixed-shape program must not ratchet a
        loaded healthy baseline down to one tiny query's time — that
        floor persists min-wins forever and would page every normal
        launch as a regression."""
        saved = {}
        kt = KernelTimer(sample_1_in=1, baseline_min_samples=4,
                         regression_window_s=1e9)
        kt.attach_baseline_store(
            load_fn=lambda: {"p": 0.002},
            save_fn=lambda prog, s: saved.__setitem__(prog, s))
        kt._fold("p", 0.0003, "k")         # one cold tiny-shape sample
        row = [r for r in kt.table() if r["program"] == "p"][0]
        assert row["baseline_s"] == pytest.approx(0.002)
        assert not saved
        # a WARMED sustained improvement still ratchets
        for _ in range(10):
            kt._fold("p", 0.0003, "k")
        row = [r for r in kt.table() if r["program"] == "p"][0]
        assert row["baseline_s"] < 0.002

    def test_baseline_never_ratchets_up(self, kt_config):
        kt = KernelTimer(sample_1_in=1, baseline_min_samples=2,
                         regression_window_s=1e9)
        kt._fold("p", 0.001, "k")
        kt._fold("p", 0.001, "k")
        base = [r for r in kt.table() if r["program"] == "p"][0]
        for _ in range(20):
            kt._fold("p", 0.01, "k")       # sustained slow
        after = [r for r in kt.table() if r["program"] == "p"][0]
        assert after["baseline_s"] == base["baseline_s"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        fr = FlightRecorder(capacity=32)
        for i in range(100):
            fr.record("tick", i=i)
        events = fr.events()
        assert len(events) == 32
        assert [e["i"] for e in events] == list(range(68, 100))
        assert [e["seq"] for e in events] == sorted(e["seq"]
                                                    for e in events)

    def test_kind_filter_and_limit(self):
        fr = FlightRecorder(capacity=64)
        for i in range(10):
            fr.record("a", i=i)
            fr.record("b", i=i)
        assert [e["i"] for e in fr.events(kind="a", limit=3)] == [7, 8, 9]

    def test_dump_to_log_never_raises(self, caplog):
        fr = FlightRecorder(capacity=16)
        fr.record("boom", detail="x" * 10)
        fr.dump_to_log("unit test")
        assert any("flight recorder dump" in r.message
                   for r in caplog.records)

    def test_resize_keeps_recent_events(self):
        fr = FlightRecorder(capacity=64)
        for i in range(40):
            fr.record("tick", i=i)
        fr.resize(16)
        assert [e["i"] for e in fr.events()] == list(range(24, 40))
        assert fr.capacity == 16


# ---------------------------------------------------------------------------
# ledger reconciliation, end to end
# ---------------------------------------------------------------------------


class TestLedgerReconciliation:
    def test_commit_query_repeat_reconciles(self):
        ms, shard = _mk_shard("dw_rec1")
        ids = _ids(shard)
        steps0 = T0 + (K - 1) * STEP
        got = shard.scan_grid(ids, F.RATE, steps0, 40, STEP, WINDOW)
        assert got is not None
        cache = _grid_cache(shard)
        _assert_reconciled(cache)
        # repeat query: zero new commits, still reconciled
        before = LEDGER.owners().get(cache.owner, {})
        assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                               WINDOW) is not None
        _assert_reconciled(cache)
        assert LEDGER.owners().get(cache.owner, {}) == before

    def test_overflow_eviction_reconciles_and_attributes(self):
        # 3 blocks of data with a budget that holds ~1.5 uncompressed
        # blocks (131072 B each): querying the tail after the head
        # forces oldest-first reclaim
        ms, shard = _mk_shard("dw_rec2", n_rows=300,
                              device_cache_bytes=200_000,
                              device_cache_compress=False)
        ids = _ids(shard)
        steps0 = T0 + (K - 1) * STEP
        assert shard.scan_grid(ids, F.RATE, steps0, 100, STEP,
                               WINDOW) is not None
        cache = _grid_cache(shard)
        _assert_reconciled(cache)
        ev = device_metrics()["evictions"]
        c0 = ev.value(owner=cache.owner, reason="budget_overflow")
        # late window: covers the last block only; earlier blocks are
        # over budget and must go
        late0 = T0 + 290 * STEP
        assert shard.scan_grid(ids, F.RATE, late0, 8, STEP,
                               WINDOW) is not None
        assert cache.evictions > 0
        assert ev.value(owner=cache.owner,
                        reason="budget_overflow") > c0
        _assert_reconciled(cache)

    def test_epoch_purge_on_new_data_reconciles(self):
        ms, shard = _mk_shard("dw_rec3")
        ids = _ids(shard)
        steps0 = T0 + (K - 1) * STEP
        assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                               WINDOW) is not None
        cache = _grid_cache(shard)
        ev0 = device_metrics()["evictions"].value(owner=cache.owner,
                                                  reason="epoch_purge")
        # new samples freeze into the covered range -> stale blocks purge
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        tags = {"__name__": "req_total", "instance": "i0", "_ws_": "w",
                "_ns_": "n"}
        for r in range(50, 60):
            b.add(int(T0 + r * STEP), [float(r)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off + 100)
        shard.flush_all()
        assert device_metrics()["evictions"].value(
            owner=cache.owner, reason="epoch_purge") > ev0
        _assert_reconciled(cache)
        # and the grid still serves (rebuilt blocks reconcile too)
        assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                               WINDOW) is not None
        _assert_reconciled(cache)

    def test_odp_churn_reconciles_and_registers_pool(self, tmp_path):
        from filodb_tpu.store.persistence import (DiskColumnStore,
                                                  DiskMetaStore)
        disk = DiskColumnStore(str(tmp_path / "c.db"))
        meta = DiskMetaStore(str(tmp_path / "m.db"))
        ms = TimeSeriesMemStore(disk, meta)
        ms_, shard = _mk_shard("dw_odp", ms=ms, groups_per_shard=2)
        ids = _ids(shard)
        steps0 = T0 + (K - 1) * STEP
        assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                               WINDOW) is not None
        cache = _grid_cache(shard)
        _assert_reconciled(cache)
        # page-out: evicting partitions purges their ledgered blocks
        assert shard.evict_partitions(3) == 3
        _assert_reconciled(cache)
        ev = device_metrics()["evictions"]
        assert ev.value(owner=shard._ledger_owner,
                        reason="epoch_purge") > 0
        # page back in (ODP), then the grid rebuilds from paged parts
        ids2 = _ids(shard)
        tags_list, _batch = shard.scan_batch(
            list(ids2) + shard.lookup_partitions(
                [ColumnFilter("_metric_", Equals("req_total"))],
                0, 2**62).missing_partkeys, 0, 2**62)
        assert shard.stats.partitions_paged >= 3
        pools = LEDGER.pools()
        assert shard._ledger_owner in pools
        assert pools[shard._ledger_owner]["bytes"] > 0
        assert pools[shard._ledger_owner]["budget"] == \
            shard.paged.max_bytes
        got = shard.scan_grid(_ids(shard), F.RATE, steps0, 40, STEP,
                              WINDOW)
        assert got is not None
        _assert_reconciled(cache)
        assert any(e["kind"] == "odp.pagein"
                   for e in FLIGHT.events(kind="odp.pagein"))

    def test_query_stats_carry_hbm_delta(self):
        """A cold grid query commits blocks; its QueryStats must show
        the positive residency delta, and a warm repeat ~zero."""
        from filodb_tpu.query.exec import ExecContext, _ACTIVE
        from filodb_tpu.query.model import QueryStats
        ms, shard = _mk_shard("dw_delta")
        ids = _ids(shard)
        steps0 = T0 + (K - 1) * STEP
        ctx = ExecContext(ms)
        _ACTIVE.ctx = ctx
        try:
            assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                                   WINDOW) is not None
        finally:
            _ACTIVE.ctx = None
        stats = QueryStats()
        ctx.fold_into(stats)
        cache = _grid_cache(shard)
        assert stats.hbm_resident_delta_bytes == \
            sum(_expected_grid_bytes(cache).values())
        ctx2 = ExecContext(ms)
        _ACTIVE.ctx = ctx2
        try:
            assert shard.scan_grid(ids, F.RATE, steps0, 40, STEP,
                                   WINDOW) is not None
        finally:
            _ACTIVE.ctx = None
        stats2 = QueryStats()
        ctx2.fold_into(stats2)
        assert stats2.hbm_resident_delta_bytes == 0


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


def _get_json(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_json(port, path, **params):
    data = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 data=data, method="POST")
    req.add_header("Content-Type", "application/x-www-form-urlencoded")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def _get_text(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as resp:
        return resp.status, resp.read().decode()


@pytest.fixture(scope="module")
def server():
    from filodb_tpu.coordinator.planner import SingleClusterPlanner
    from filodb_tpu.core.schemas import DatasetOptions
    from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
    from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus

    mapper = ShardMapper(1)
    mapper.register_node(range(1), "local")
    mapper.update_status(0, ShardStatus.ACTIVE)
    ms, _shard = _mk_shard("dw_http")
    planner = SingleClusterPlanner("dw_http", mapper, DatasetOptions(),
                                   spread_default=0)
    srv = FiloHttpServer()
    srv.bind_dataset(DatasetBinding("dw_http", ms, planner))
    port = srv.start()
    yield port, ms
    srv.shutdown()


class TestEndpoints:
    def _warm(self, port):
        code, body = _get_json(
            port, "/promql/dw_http/api/v1/query_range",
            query='sum(rate(req_total{_ws_="w",_ns_="n"}[5m]))',
            start=str((T0 + (K - 1) * STEP) // 1000),
            end=str((T0 + 45 * STEP) // 1000), step="60s", stats="true")
        assert code == 200 and body["data"]["result"]
        return body

    def test_admin_device_reconciles(self, server):
        port, ms = server
        self._warm(port)
        code, body = _get_json(port, "/admin/device")
        assert code == 200
        data = body["data"]
        shard = ms.shards("dw_http")[0]
        cache = _grid_cache(shard)
        gc.collect()
        owners = data["ledger"]["owners"]
        got = {fmt: row["bytes"] for fmt, row in
               owners.get(cache.owner, {}).items() if row["bytes"]}
        want = {fmt: n for fmt, n in _expected_grid_bytes(cache).items()
                if n}
        assert got == want
        rows = [r for r in data["arenas"]["dw_http"]
                if r["arena"] == "device-grid"]
        assert rows and rows[0]["bytes_resident"] > 0
        assert rows[0]["budget"] == cache.budget
        assert data["compile"]["programs"], "compile table empty"
        assert "devices" in data and "flight_recorder" in data

    def test_stats_carry_hbm_delta_field(self, server):
        port, _ms = server
        body = self._warm(port)
        samples = body["data"]["stats"]["samples"]
        assert "hbmResidentDeltaBytes" in samples

    def test_metrics_exposition_has_device_families(self, server):
        port, _ms = server
        self._warm(port)
        code, text = _get_text(port, "/metrics")
        assert code == 200
        assert "filodb_device_hbm_bytes{" in text
        assert "filodb_jit_compiles_total{" in text
        assert "filodb_device_evictions_total" in text \
            or "# TYPE filodb_device_evictions_total" in text
        assert "filodb_process_resident_memory_bytes" in text
        assert "filodb_process_open_fds" in text
        assert "filodb_process_threads" in text
        assert "filodb_process_uptime_seconds" in text
        assert "filodb_process_gc_collections{" in text

    def test_flightrecorder_endpoint(self, server):
        port, _ms = server
        self._warm(port)
        code, body = _get_json(port, "/admin/flightrecorder", limit=1000)
        assert code == 200
        kinds = {e["kind"] for e in body["data"]["events"]}
        assert "query.start" in kinds and "query.end" in kinds
        assert "jit.compile" in kinds
        code, body = _get_json(port, "/admin/flightrecorder",
                               kind="query.end", limit=5)
        assert all(e["kind"] == "query.end"
                   for e in body["data"]["events"])

    def test_admin_config_get_and_post(self, server):
        from filodb_tpu.utils.forensics import TRACE_STORE
        port, _ms = server
        code, body = _get_json(port, "/admin/config")
        assert code == 200
        data = body["data"]
        assert data["datasets"]["dw_http"]["device_cache_bytes"] > 0
        assert "slow-query-threshold-s" in data["observability"]
        old = TRACE_STORE.slow_threshold_s
        try:
            code, body = _post_json(port, "/admin/config",
                                    **{"slow-query-threshold-s": "7.5"})
            assert code == 200
            assert body["data"]["observability"][
                "slow-query-threshold-s"] == 7.5
            assert TRACE_STORE.slow_threshold_s == 7.5
        finally:
            TRACE_STORE.slow_threshold_s = old
        code, _body = _get_json(port, "/admin/config",
                                **{"slow-query-threshold-s": "-1"})
        assert code == 400


# ---------------------------------------------------------------------------
# kernel flight deck over HTTP: /admin/kernels, stats devicePrograms,
# /debug/device_profilez (ISSUE 15)
# ---------------------------------------------------------------------------


class TestKernelDeckEndpoints:
    def _warm(self, port, stats="true"):
        code, body = _get_json(
            port, "/promql/dw_http/api/v1/query_range",
            query='sum(rate(req_total{_ws_="w",_ns_="n"}[5m]))',
            start=str((T0 + (K - 1) * STEP) // 1000),
            end=str((T0 + 45 * STEP) // 1000), step="60s", stats=stats)
        assert code == 200 and body["data"]["result"]
        return body

    def test_device_programs_reconcile_with_device_compute(
            self, server, kt_config):
        """ISSUE 15 acceptance: on a sampled query the per-program
        devicePrograms seconds sum to (at most, within tolerance) the
        device_compute stage bucket that wraps the same launches."""
        port, _ms = server
        kt_config.configure(sample_1_in=1)
        self._warm(port)                       # compiles never fold
        body = self._warm(port)
        stats = body["data"]["stats"]
        dp = stats["devicePrograms"]
        assert dp, "sampled query carried no devicePrograms split"
        assert all(v >= 0 for v in dp.values())
        total = sum(dp.values())
        assert total > 0
        # the sampled block_until_ready waits run INSIDE the
        # device_compute wall-time window; tolerance covers the
        # perf_counter stamps around the wrapper
        assert total <= stats["timings"]["device_compute"] + 0.005

    def test_admin_kernels_joins_and_reconciles_exactly(self, server,
                                                        kt_config):
        port, _ms = server
        kt_config.configure(sample_1_in=1)
        self._warm(port)
        self._warm(port)
        code, body = _get_json(port, "/admin/kernels")
        assert code == 200
        data = body["data"]
        assert data["sample_1_in"] == 1
        assert data["hbm_roof_bytes_per_s"] > 0
        rows = {r["program"]: r for r in data["programs"]}
        # a devicestore program THIS test's 1-in-1 queries sampled
        # (earlier tests at the default rate leave bytes-only rows)
        served = [r for p, r in rows.items()
                  if p.startswith("devicestore.") and r["bytes_total"]
                  and r["ewma_device_s"] is not None]
        assert served, f"no sampled devicestore program: {sorted(rows)}"
        row = served[0]
        # the compile-table join and the live roofline position
        assert row["compiles"] >= 1
        assert row["ewma_device_s"] is not None
        assert row["roofline_fraction"] is not None \
            and row["roofline_fraction"] > 0
        # launches x sample-rate reconciliation, EXACT: the table's
        # launch count is counted on every launch, as is the counter
        m = device_metrics()["kernel_launches"]
        for program, r in rows.items():
            assert m.value(program=program) == r["launches"], program

    def test_roofline_degrades_and_row_flags_regression(self, server,
                                                        kt_config):
        """ISSUE 15 acceptance: an injected slowdown on the serving
        program degrades its /admin/kernels roofline fraction and flips
        the row's sentry state."""
        from filodb_tpu.integrity.faultinject import (
            clear_kernel_slowdown, inject_kernel_slowdown)
        port, _ms = server
        kt_config.configure(sample_1_in=1, baseline_min_samples=2,
                            regression_window_s=0.05,
                            regression_factor=1.5)
        launches0 = {r["program"]: r["launches"]
                     for r in KERNEL_TIMER.table()}
        for _ in range(4):
            self._warm(port)
        code, body = _get_json(port, "/admin/kernels")
        rows = {r["program"]: r for r in body["data"]["programs"]}
        # the program THIS query actually launches (in a full-suite run
        # other devicestore programs carry history but never launch
        # here, so slowing them would never sample)
        prog, before = next(
            (p, r) for p, r in rows.items()
            if p.startswith("devicestore.") and r["roofline_fraction"]
            and r["launches"] > launches0.get(p, 0))
        inject_kernel_slowdown(prog, 0.01)
        try:
            for _ in range(30):
                self._warm(port, stats="false")
                if _kt_row(prog)["regressed"]:
                    break
        finally:
            clear_kernel_slowdown(prog)
        code, body = _get_json(port, "/admin/kernels")
        row = {r["program"]: r
               for r in body["data"]["programs"]}[prog]
        assert row["regressed"] and row["episodes"] >= 1
        assert row["roofline_fraction"] < before["roofline_fraction"]
        # recover so the shared timer leaves the fixture healthy
        for _ in range(100):
            self._warm(port, stats="false")
            if not _kt_row(prog)["regressed"]:
                break
        assert not _kt_row(prog)["regressed"]

    def test_device_profilez_captures_and_shares_single_flight(self,
                                                               server):
        import os
        port, _ms = server
        code, body = _get_json(port, "/debug/device_profilez",
                               seconds="0.05")
        assert code == 200, body
        data = body["data"]
        assert os.path.isdir(data["trace_dir"])
        assert data["files"] >= 1, "trace capture produced no files"
        # ONE single-flight guard across BOTH profile surfaces: with
        # the lock held, host and device profiling each answer 503
        from filodb_tpu.utils import forensics
        assert forensics._PROFILE_LOCK.acquire(blocking=False)
        try:
            code, _b = _get_json(port, "/debug/profilez", seconds="0.05")
            assert code == 503
            code, _b = _get_json(port, "/debug/device_profilez",
                                 seconds="0.05")
            assert code == 503
        finally:
            forensics._PROFILE_LOCK.release()

    def test_device_trace_dirs_are_retention_bounded(self, tmp_path):
        """Review fix: repeated captures must not fill the disk — at
        most DEVICE_TRACE_RETAIN capture dirs survive, oldest pruned."""
        import os
        from filodb_tpu.utils import forensics
        old = forensics.DEVICE_TRACE_RETAIN
        forensics.DEVICE_TRACE_RETAIN = 2
        try:
            for _ in range(4):
                got = forensics.device_profile(seconds=0.05,
                                               trace_root=str(tmp_path))
            assert got["retained"] == 2
            dirs = [e for e in os.listdir(tmp_path)
                    if e.startswith("trace-")]
            assert len(dirs) == 2, sorted(dirs)
            # the newest capture always survives its own prune
            assert os.path.basename(got["trace_dir"]) in dirs
        finally:
            forensics.DEVICE_TRACE_RETAIN = old

    def test_admin_config_kernel_knobs(self, server, kt_config):
        port, _ms = server
        code, body = _get_json(port, "/admin/config")
        assert code == 200
        obs = body["data"]["observability"]
        assert "kernel-sample-1-in" in obs
        assert "hbm-roof-bytes-per-s" in obs
        code, body = _post_json(port, "/admin/config",
                                **{"kernel-sample-1-in": "8",
                                   "hbm-roof-bytes-per-s": "1e9",
                                   "kernel-regression-factor": "2.0",
                                   "kernel-baseline-min-samples": "5"})
        assert code == 200
        obs = body["data"]["observability"]
        assert obs["kernel-sample-1-in"] == 8
        assert obs["hbm-roof-bytes-per-s"] == 1e9
        assert obs["kernel-regression-factor"] == 2.0
        assert obs["kernel-baseline-min-samples"] == 5
        assert KERNEL_TIMER.sample_1_in == 8

    def test_metrics_exposition_has_kernel_families(self, server,
                                                    kt_config):
        port, _ms = server
        kt_config.configure(sample_1_in=1)
        self._warm(port)
        code, text = _get_text(port, "/metrics")
        assert code == 200
        assert "filodb_kernel_launches_total{" in text
        assert "filodb_kernel_device_seconds{" in text
        assert "filodb_kernel_roofline_fraction{" in text
