"""C++ CPU baseline (native/src/baseline.cpp) vs the bench.py numpy oracle.

The baseline is the honest stand-in for the JVM's per-row iterator path
(BASELINE.md protocol; reference: jmh/QueryInMemoryBenchmark.scala:45-249),
so its semantics must match the oracle bit-for-bit — counter correction,
Prometheus extrapolation, group sum — including on gappy/reset data.
"""

import numpy as np
import pytest

from filodb_tpu.native import baseline

pytestmark = pytest.mark.skipif(
    not baseline.available(),
    reason=f"baseline lib unavailable: {baseline.build_error()}")

WINDOW_MS = 300_000


def _oracle_rate_sum(ts, vals, ids, n_groups, steps):
    import bench
    saved = bench.WINDOW_MS
    assert saved == WINDOW_MS
    return bench._numpy_rate_sum(ts, vals, ids, steps)


def _gen(seed, S=37, R=64, n_groups=5, gap_frac=0.2, resets=True):
    rng = np.random.default_rng(seed)
    base = 600_000
    step = 10_000
    ts = (base + np.arange(R, dtype=np.int64) * step
          + rng.integers(0, step // 2, (S, R)))
    ts = np.sort(ts, axis=1)
    incr = rng.uniform(0, 10, (S, R))
    vals = np.cumsum(incr, axis=1)
    if resets:
        # counter resets: zero the running value at random positions
        for s in range(S):
            for pos in rng.integers(1, R, size=2):
                vals[s, pos:] -= vals[s, pos]
    mask = rng.random((S, R)) < gap_frac
    vals = np.where(mask, np.nan, vals)
    ids = rng.integers(0, n_groups, S).astype(np.int32)
    steps = np.arange(base + WINDOW_MS, base + R * step, 60_000,
                      dtype=np.int64)
    return ts, vals, ids, steps


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_rate_sum_matches_oracle(seed):
    ts, vals, ids, steps = _gen(seed)
    got = baseline.rate_sum(ts, vals, ids, 5, steps, WINDOW_MS)
    want = _oracle_rate_sum(ts, vals, ids, 5, steps)
    np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)


def test_rate_sum_multithreaded_matches_single():
    ts, vals, ids, steps = _gen(7, S=101)
    one = baseline.rate_sum(ts, vals, ids, 5, steps, WINDOW_MS, nthreads=1)
    four = baseline.rate_sum(ts, vals, ids, 5, steps, WINDOW_MS, nthreads=4)
    np.testing.assert_allclose(one, four, rtol=1e-12, equal_nan=True)


def test_rate_sum_rejects_bad_group_ids():
    ts, vals, ids, steps = _gen(3, S=8)
    ids[3] = 99
    with pytest.raises(ValueError):
        baseline.rate_sum(ts, vals, ids, 5, steps, WINDOW_MS)


def test_sum_over_time_matches_numpy():
    ts, vals, ids, steps = _gen(4, S=23)
    got = baseline.sum_over_time_sum(ts, vals, ids, 5, steps, WINDOW_MS)
    G = 5
    want = np.zeros((G, len(steps)))
    cnt = np.zeros((G, len(steps)))
    for s in range(ts.shape[0]):
        fin = np.isfinite(vals[s])
        t_row, v_row = ts[s][fin], vals[s][fin]
        for j, st in enumerate(steps):
            sel = (t_row > st - WINDOW_MS) & (t_row <= st)
            if sel.any():
                want[ids[s], j] += v_row[sel].sum()
                cnt[ids[s], j] += 1
    want = np.where(cnt > 0, want, np.nan)
    np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)


def test_all_nan_series_contributes_nothing():
    ts, vals, ids, steps = _gen(5, S=4)
    vals[:] = np.nan
    got = baseline.rate_sum(ts, vals, ids, 5, steps, WINDOW_MS)
    assert np.isnan(got).all()
