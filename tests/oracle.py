"""Brute-force numpy oracle for windowed range functions.

Independent, per-series, per-window loop implementation of the Prometheus /
reference semantics (window = (t-w, t]; extrapolatedRate per
RateFunctions.scala) used to validate the vectorized device kernels —
mirrors the reference's test strategy of comparing chunked vs sliding vs
brute force (AggrOverTimeFunctionsSpec)."""

from __future__ import annotations

import numpy as np


def window_indices(ts: np.ndarray, t: int, window: int) -> np.ndarray:
    return np.nonzero((ts > t - window) & (ts <= t))[0]


def counter_correct(vals: np.ndarray) -> np.ndarray:
    out = vals.astype(np.float64).copy()
    corr = 0.0
    prev_raw = None
    for i in range(len(vals)):
        if prev_raw is not None and vals[i] < prev_raw:
            corr += prev_raw
        out[i] = vals[i] + corr
        prev_raw = vals[i]
    return out


def extrapolated_rate(wstart, wend, ts_w, vals_w, is_counter, is_rate):
    n = len(ts_w)
    if n < 2:
        return np.nan
    t1, t2 = ts_w[0], ts_w[-1]
    v1, v2 = vals_w[0], vals_w[-1]
    dur_start = (t1 - wstart) / 1000.0
    dur_end = (wend - t2) / 1000.0
    sampled = (t2 - t1) / 1000.0
    if sampled <= 0:
        return np.nan
    avg_dur = sampled / (n - 1)
    delta = v2 - v1
    if is_counter and delta > 0 and v1 >= 0:
        dur_zero = sampled * (v1 / delta)
        if dur_zero < dur_start:
            dur_start = dur_zero
    thresh = avg_dur * 1.1
    extrap = sampled
    extrap += dur_start if dur_start < thresh else avg_dur / 2
    extrap += dur_end if dur_end < thresh else avg_dur / 2
    scaled = delta * (extrap / sampled)
    if is_rate:
        return scaled / (wend - wstart) * 1000.0
    return scaled


def range_fn(name: str, ts: np.ndarray, vals: np.ndarray, start: int, end: int,
             step: int, window: int, **params) -> np.ndarray:
    """Evaluate one range function for one series over the step grid."""
    steps = np.arange(start, end + 1, step)
    out = np.full(len(steps), np.nan)
    corrected = counter_correct(vals) if name in ("rate", "increase", "irate") else vals
    for j, t in enumerate(steps):
        w = window_indices(ts, t, window)
        vw = vals[w]
        cw = corrected[w]
        fin = np.isfinite(vw)
        if name in ("rate", "increase", "delta"):
            # NaN rows are "no sample": boundaries come from finite samples
            wf = w[fin]
            if len(wf) >= 2:
                out[j] = extrapolated_rate(t - window, t, ts[wf], corrected[wf],
                                           is_counter=name != "delta",
                                           is_rate=name == "rate")
        elif name in ("irate", "idelta"):
            wf = w[fin]
            if len(wf) >= 2:
                dt = (ts[wf][-1] - ts[wf][-2]) / 1000.0
                dv = corrected[wf][-1] - corrected[wf][-2]
                out[j] = dv / dt if name == "irate" and dt > 0 else (
                    dv if name == "idelta" else np.nan)
        elif name == "sum_over_time":
            if fin.any():
                out[j] = np.sum(vw[fin])
        elif name == "count_over_time":
            if fin.any():
                out[j] = fin.sum()
        elif name == "avg_over_time":
            if fin.any():
                out[j] = np.mean(vw[fin])
        elif name == "min_over_time":
            if fin.any():
                out[j] = np.min(vw[fin])
        elif name == "max_over_time":
            if fin.any():
                out[j] = np.max(vw[fin])
        elif name == "stdvar_over_time":
            if fin.any():
                out[j] = np.var(vw[fin])
        elif name == "stddev_over_time":
            if fin.any():
                out[j] = np.std(vw[fin])
        elif name == "changes":
            if fin.any():
                c = 0
                for i in range(1, len(w)):
                    a, b = vals[w[i - 1]], vals[w[i]]
                    if np.isfinite(a) and np.isfinite(b) and a != b:
                        c += 1
                out[j] = c
        elif name == "resets":
            if fin.any():
                c = 0
                for i in range(1, len(w)):
                    if vals[w[i]] < vals[w[i - 1]]:
                        c += 1
                out[j] = c
        elif name == "last":
            fi = np.nonzero(fin)[0]
            if len(fi):
                out[j] = vw[fi[-1]]
        elif name == "timestamp":
            fi = np.nonzero(fin)[0]
            if len(fi):
                out[j] = ts[w][fi[-1]] / 1000.0
        elif name == "quantile_over_time":
            if fin.any():
                out[j] = np.quantile(vw[fin], params["q"])
        elif name == "deriv":
            if fin.sum() >= 2:
                x = (ts[w][fin] - t) / 1000.0
                y = vw[fin]
                if np.var(x) > 0:
                    slope = np.cov(x, y, bias=True)[0, 1] / np.var(x)
                    out[j] = slope
        elif name == "predict_linear":
            if fin.sum() >= 2:
                x = (ts[w][fin] - t) / 1000.0
                y = vw[fin]
                if np.var(x) > 0:
                    slope = np.cov(x, y, bias=True)[0, 1] / np.var(x)
                    intercept = y.mean() - slope * x.mean()
                    out[j] = intercept + slope * params["duration_s"]
        elif name == "z_score":
            fi = np.nonzero(fin)[0]
            if len(fi):
                sd = np.std(vw[fin])
                # sd == 0 (constant window) divides 0/0 -> NaN, which IS
                # the reference semantics; silence the RuntimeWarning the
                # scalar divide would otherwise emit on every suite run
                with np.errstate(invalid="ignore", divide="ignore"):
                    out[j] = (vw[fi[-1]] - np.mean(vw[fin])) / sd
        elif name == "holt_winters":
            y = vw[fin]
            if len(y) >= 2:
                sf, tf = params["sf"], params["tf"]
                s, b = y[0], y[1] - y[0]
                for i in range(1, len(y)):
                    x = sf * y[i] + (1 - sf) * (s + b)
                    b = tf * (x - s) + (1 - tf) * b
                    s = x
                out[j] = s
        elif name == "mad_over_time":
            if fin.any():
                med = np.quantile(vw[fin], 0.5)
                out[j] = np.quantile(np.abs(vw[fin] - med), 0.5)
        else:
            raise ValueError(f"unknown oracle function {name}")
    return out
