"""Cold tier (ISSUE 16, doc/coldstore.md): bucket, age-out, chaos, stitch.

Oracle strategy: the all-resident local store is ground truth — after
any sequence of age-out passes, every read (store-level merge, ODP
page-in, stitched router query) must be BIT-equal to what the fully
local store served before migration.  Chaos (truncated / corrupt /
stalled bucket objects) must degrade LOUDLY — quarantine + partial-
results accounting or a deadline refusal — never into silent wrong
answers.
"""

import threading
import time

import numpy as np
import pytest

from filodb_tpu.coldstore import (AgeOutManager, BucketTimeout,
                                  ColdChunkStore, LocalFSBucket,
                                  ObjectMissing, TieredColumnStore)
from filodb_tpu.coldstore.store import object_key, parse_object_key
from filodb_tpu.core.chunk import ChunkSet, ChunkSetInfo
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.downsample.dsstore import ds_dataset_name
from filodb_tpu.integrity import QUARANTINE, chunk_crc
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query.exec import ExecContext
from filodb_tpu.query.model import QueryContext
from filodb_tpu.rollup.config import RollupConfig
from filodb_tpu.rollup.engine import RollupEngine
from filodb_tpu.rollup.planner import (RollupRouterPlanner,
                                       canonical_tiers)
from filodb_tpu.store.persistence import DiskColumnStore, DiskMetaStore
from filodb_tpu.utils.observability import coldstore_metrics

T0 = 1_700_000_000_000
STEP = 10_000
N_SERIES = 5
N_ROWS = 40
FILTERS = [ColumnFilter("_metric_", Equals("cm"))]


@pytest.fixture(autouse=True)
def _clean_quarantine():
    QUARANTINE.clear()
    yield
    QUARANTINE.clear()


def _counters() -> dict:
    return {k: m.total() for k, m in coldstore_metrics().items()}


def _mk_chunkset(cid: int, base: int, payload: bytes) -> ChunkSet:
    return ChunkSet(ChunkSetInfo(chunk_id=cid, num_rows=10,
                                 start_time=base, end_time=base + 9_000),
                    partkey=b"pk0", vectors=[payload, payload[::-1]])


def _build_persisted(tmp_path, n_series=N_SERIES, n_rows=N_ROWS,
                     store=None):
    """Ingest + flush a small gauge dataset into a disk store."""
    disk = store if store is not None \
        else DiskColumnStore(str(tmp_path / "chunks.db"))
    meta = DiskMetaStore(str(tmp_path / "meta.db"))
    ms = TimeSeriesMemStore(disk, meta)
    sh = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    ts = T0 + np.arange(n_rows, dtype=np.int64) * STEP
    rng = np.random.default_rng(7)
    for i in range(n_series):
        b.add_series(ts, [rng.random(n_rows) + i],
                     {"_metric_": "cm", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"})
    for off, c in enumerate(b.containers()):
        sh.ingest_container(c, off)
    sh.flush_all(ingestion_time=1000)
    return disk, meta, ms, sh


def _tiered(tmp_path):
    local = DiskColumnStore(str(tmp_path / "chunks.db"))
    bucket = LocalFSBucket(str(tmp_path / "bucket"))
    cold = ColdChunkStore(bucket, fetch_timeout_s=10.0)
    return TieredColumnStore(local, cold), local, cold, bucket


def _scan(shard):
    res = shard.lookup_partitions(FILTERS, 0, 2 ** 62)
    return shard.scan_batch(res.part_ids, 0, 2 ** 62)


def _snapshot(shard) -> dict:
    """{inst: (ts list, vals list)} — the bit-equality unit."""
    tags, batch = _scan(shard)
    out = {}
    for i, t in enumerate(tags):
        n = int(batch.row_counts[i])
        out[t["inst"]] = (batch.timestamps[i, :n].tolist(),
                          batch.values[i, :n].tolist())
    return out


# ---------------------------------------------------------------------------
# Bucket + key codec
# ---------------------------------------------------------------------------


class TestBucket:
    def test_roundtrip_list_delete(self, tmp_path):
        b = LocalFSBucket(str(tmp_path / "b"))
        b.put_object("chunks/a/1", b"one")
        b.put_object("chunks/a/2", b"twotwo")
        assert b.get_object("chunks/a/1", timeout_s=5) == b"one"
        assert b.list_objects("chunks/a/") == [("chunks/a/1", 3),
                                               ("chunks/a/2", 6)]
        assert b.delete_object("chunks/a/1") is True
        assert b.delete_object("chunks/a/1") is False
        with pytest.raises(ObjectMissing):
            b.get_object("chunks/a/1", timeout_s=5)

    def test_bad_keys_rejected(self, tmp_path):
        b = LocalFSBucket(str(tmp_path / "b"))
        for bad in ("", "/abs", "a/../b"):
            with pytest.raises(ValueError):
                b.put_object(bad, b"x")

    def test_exhausted_budget_refuses_without_io(self, tmp_path):
        b = LocalFSBucket(str(tmp_path / "b"))
        b.put_object("chunks/k", b"v")
        with pytest.raises(BucketTimeout):
            b.get_object("chunks/k", timeout_s=0)
        with pytest.raises(BucketTimeout):
            b.get_object("chunks/k", timeout_s=-1)

    def test_stall_bounded_by_timeout(self, tmp_path):
        """A stalled backend delays at most timeout_s, then refuses —
        the caller is late, never wedged."""
        b = LocalFSBucket(str(tmp_path / "b"))
        b.put_object("chunks/k", b"v")
        b.stall_s = 60.0
        t0 = time.monotonic()
        with pytest.raises(BucketTimeout):
            b.get_object("chunks/k", timeout_s=0.05)
        assert time.monotonic() - t0 < 5.0

    def test_object_key_roundtrip(self):
        key = object_key("prom", 3, b"\x01pk", 42, 100, T0, T0 + 9_000,
                         7, 1234, 0xDEADBEEF)
        meta = parse_object_key(key, size=10)
        assert meta is not None
        assert (meta.partkey, meta.chunk_id, meta.num_rows,
                meta.start_time, meta.end_time, meta.schema_hash,
                meta.ingestion_time, meta.crc, meta.size) == \
            (b"\x01pk", 42, 100, T0, T0 + 9_000, 7, 1234, 0xDEADBEEF, 10)
        assert parse_object_key("chunks/x/not-a-chunk", 1) is None


# ---------------------------------------------------------------------------
# ColdChunkStore + TieredColumnStore merge
# ---------------------------------------------------------------------------


class TestTieredMerge:
    def test_rows_identical_before_and_after_ageout(self, tmp_path):
        tiered, local, cold, _bucket = _tiered(tmp_path)
        css = [_mk_chunkset(cid, T0 + cid * 10_000, b"PAY%d" % cid * 30)
               for cid in range(6)]
        local.initialize("prom", 1)
        local.write_chunks("prom", 0, css, ingestion_time=999)
        before = tiered.read_raw_rows("prom", 0, [b"pk0"], 0, 2 ** 62)
        mgr = AgeOutManager(local, cold,
                            now_ms_fn=lambda: T0 + 6 * 10_000 + 10)
        # retention 25s: chunks ending before T0+35s age out (first 3)
        rep = mgr.run("prom", 25_000 + 10)
        assert rep["total_chunks"] == 3
        assert local.num_chunks("prom", 0) == 3
        after = tiered.read_raw_rows("prom", 0, [b"pk0"], 0, 2 ** 62)
        assert after == before  # bit-equal merge, cold rows included
        # partition-shaped reads merge and order by chunk_id too
        parts = dict(tiered.read_raw_partitions("prom", 0, [b"pk0"],
                                                0, 2 ** 62))
        assert [cs.info.chunk_id for cs in parts[b"pk0"]] == list(range(6))

    def test_local_wins_overlap_and_reupload_idempotent(self, tmp_path):
        """Crash window: a row uploaded but not yet deleted locally is
        served once (local copy) and re-aged without error."""
        tiered, local, cold, _bucket = _tiered(tmp_path)
        cs = _mk_chunkset(1, T0, b"OVERLAP" * 20)
        local.initialize("prom", 1)
        local.write_chunks("prom", 0, [cs], ingestion_time=5)
        blob_rows = local.read_raw_rows("prom", 0, [b"pk0"], 0, 2 ** 62)
        (pk, cid, nr, st, et, sch, blob, crc) = blob_rows[0][:8]
        cold.put_chunk_row("prom", 0, pk, cid, nr, st, et, sch, 5,
                           bytes(blob), crc, verify=True)
        rows = tiered.read_raw_rows("prom", 0, [b"pk0"], 0, 2 ** 62)
        assert len(rows) == 1  # deduped, not doubled
        mgr = AgeOutManager(local, cold, now_ms_fn=lambda: et + 10)
        rep = mgr.run("prom", 1)   # re-uploads the same key, then deletes
        assert rep["total_chunks"] == 1
        assert local.num_chunks("prom", 0) == 0
        rows2 = tiered.read_raw_rows("prom", 0, [b"pk0"], 0, 2 ** 62)
        assert rows2 == rows

    def test_sqlite_admin_surface_delegates(self, tmp_path):
        tiered, local, _cold, _bucket = _tiered(tmp_path)
        local.initialize("prom", 1)
        # fault injection + verify-chunks reach sqlite through the wrap
        assert tiered._conn() is local._conn()
        assert tiered.list_shards("prom") == []


# ---------------------------------------------------------------------------
# Age-out machinery
# ---------------------------------------------------------------------------


class TestAgeOut:
    def test_plan_is_dry(self, tmp_path):
        _tiered_, local, cold, _bucket = _tiered(tmp_path)
        local.initialize("prom", 1)
        local.write_chunks("prom", 0, [_mk_chunkset(1, T0, b"X" * 50)],
                           ingestion_time=1)
        mgr = AgeOutManager(local, cold, now_ms_fn=lambda: T0 + 10 ** 9)
        plan = mgr.plan("prom", 1)
        assert plan["total_chunks"] == 1 and plan["total_bytes"] > 0
        assert local.num_chunks("prom", 0) == 1      # nothing moved
        assert cold.num_chunks("prom", 0) == 0
        assert mgr.floor_ms("prom") == 0             # no watermark yet

    def test_watermark_persists_and_floors(self, tmp_path):
        _t, local, cold, _bucket = _tiered(tmp_path)
        meta = DiskMetaStore(str(tmp_path / "meta.db"))
        meta.initialize()
        local.initialize("prom", 2)
        for sh in (0, 1):
            local.write_chunks("prom", sh,
                               [_mk_chunkset(1, T0, b"W" * 40)],
                               ingestion_time=1)
        now = T0 + 100_000
        mgr = AgeOutManager(local, cold, metastore=meta,
                            now_ms_fn=lambda: now)
        mgr.run("prom", 1_000, shards=[0])
        assert mgr.watermark_ms("prom", 0) == now - 1_000
        assert mgr.watermark_ms("prom", 1) == 0   # never completed a pass
        mgr.run("prom", 2_000, shards=[1])
        assert mgr.floor_ms("prom") == now - 2_000   # min across shards
        # a FRESH manager reloads the watermarks from the metastore KV
        mgr2 = AgeOutManager(local, cold, metastore=meta,
                             now_ms_fn=lambda: now)
        assert mgr2.watermark_ms("prom", 0) == now - 1_000
        assert mgr2.floor_ms("prom") == now - 2_000

    def test_idempotent_second_pass(self, tmp_path):
        _t, local, cold, _bucket = _tiered(tmp_path)
        local.initialize("prom", 1)
        local.write_chunks("prom", 0, [_mk_chunkset(1, T0, b"I" * 40)],
                           ingestion_time=1)
        mgr = AgeOutManager(local, cold, now_ms_fn=lambda: T0 + 10 ** 9)
        assert mgr.run("prom", 1)["total_chunks"] == 1
        assert mgr.run("prom", 1)["total_chunks"] == 0
        assert cold.num_chunks("prom", 0) == 1

    def test_corrupt_local_row_never_archived(self, tmp_path):
        """The verified scan quarantines + skips a corrupt local row —
        corruption is never uploaded as truth, and the pass still
        completes for the healthy rows."""
        from filodb_tpu.integrity.faultinject import FaultInjector
        _t, local, cold, bucket = _tiered(tmp_path)
        local.initialize("prom", 1)
        css = [_mk_chunkset(cid, T0 + cid * 10_000, b"C%d" % cid * 40)
               for cid in range(3)]
        local.write_chunks("prom", 0, css, ingestion_time=1)
        pk, cid = FaultInjector(3).corrupt_stored_chunk(local, "prom", 0,
                                                        mode="flip")
        mgr = AgeOutManager(local, cold, now_ms_fn=lambda: T0 + 10 ** 9)
        rep = mgr.run("prom", 1)
        assert rep["total_chunks"] == 2
        assert QUARANTINE.is_quarantined(pk, cid)
        archived = {m.chunk_id for m in
                    cold._select("prom", 0, None, 0, 2 ** 62)}
        assert cid not in archived and len(archived) == 2


# ---------------------------------------------------------------------------
# ODP paging through the cold tier + chaos
# ---------------------------------------------------------------------------


def _age_out_everything(local, cold, meta=None):
    mgr = AgeOutManager(local, cold, metastore=meta,
                        now_ms_fn=lambda: T0 + 10 ** 10)
    return mgr.run("prom", 1)


class TestColdPaging:
    def test_paged_scan_bitequal_to_resident(self, tmp_path):
        tiered, local, cold, _bucket = _tiered(tmp_path)
        disk, meta, ms, sh = _build_persisted(tmp_path, store=tiered)
        want = _snapshot(sh)
        rep = _age_out_everything(local, cold)
        assert rep["total_chunks"] > 0
        assert local.num_chunks("prom", 0) == 0
        before = _counters()
        fresh = TimeSeriesMemStore(tiered, meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        assert fresh.recover_index("prom", 0) == N_SERIES
        got = _snapshot(fresh.get_shard("prom", 0))
        assert got == want  # every sample paged back from the bucket
        after = _counters()
        assert after["fetches"] - before["fetches"] >= rep["total_chunks"]
        assert after["fetch_bytes"] > before["fetch_bytes"]
        assert cold.cold_page_bytes("prom", 0) > 0
        # the fetched bytes get their own fmt=cold-page HBM-ledger row
        from filodb_tpu.utils.devicewatch import LEDGER
        assert LEDGER.pools().get("coldstore:prom/0", {}).get("bytes", 0) \
            == cold.cold_page_bytes("prom", 0)
        cold.shutdown()
        assert "coldstore:prom/0" not in LEDGER.pools()

    @pytest.mark.parametrize("mode", ["flip", "truncate"])
    def test_corrupt_object_quarantined_not_served(self, tmp_path, mode):
        """A damaged bucket object (bit flip / truncation) is dropped at
        CRC-on-fetch: the scan serves the surviving series, the chunk is
        quarantined, the corrupt-fetch counter bumps — the bad bytes are
        NEVER decoded into results."""
        tiered, local, cold, bucket = _tiered(tmp_path)
        disk, meta, ms, sh = _build_persisted(tmp_path, store=tiered)
        _age_out_everything(local, cold)
        victim = bucket.object_keys()[0]
        bucket.corrupt_object(victim, mode=mode)
        meta_v = parse_object_key(victim,
                                  size=len(bucket.get_object(
                                      victim, timeout_s=5)))
        before = _counters()
        fresh = TimeSeriesMemStore(tiered, meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        fresh.recover_index("prom", 0)
        tags, _batch = _scan(fresh.get_shard("prom", 0))
        assert len(tags) == N_SERIES - 1
        assert QUARANTINE.is_quarantined(meta_v.partkey, meta_v.chunk_id)
        after = _counters()
        assert after["fetch_corrupt"] - before["fetch_corrupt"] == 1

    def test_stalled_bucket_is_deadline_refusal_not_wedge(self, tmp_path):
        tiered, local, cold, bucket = _tiered(tmp_path)
        disk, meta, ms, sh = _build_persisted(tmp_path, store=tiered)
        _age_out_everything(local, cold)
        bucket.stall_s = 60.0
        cold.fetch_timeout_s = 0.2
        before = _counters()
        fresh = TimeSeriesMemStore(tiered, meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        fresh.recover_index("prom", 0)
        shard = fresh.get_shard("prom", 0)
        done = threading.Event()
        err: list = []

        def run():
            try:
                _scan(shard)
            except BucketTimeout as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t0 = time.monotonic()
        t.start()
        assert done.wait(30.0), "scan wedged on a stalled bucket"
        assert err, "stalled fetch must refuse loudly, not serve"
        assert time.monotonic() - t0 < 30.0
        after = _counters()
        assert after["fetch_timeouts"] > before["fetch_timeouts"]
        # nothing was quarantined — a stall is an availability event,
        # not data corruption
        assert not QUARANTINE.is_quarantined(b"", 0)

    def test_byte_cap_enforced_before_any_fetch(self, tmp_path):
        from filodb_tpu.store.columnstore import ScanBytesExceeded
        tiered, local, cold, bucket = _tiered(tmp_path)
        disk, meta, ms, sh = _build_persisted(tmp_path, store=tiered)
        _age_out_everything(local, cold)
        before = _counters()
        with pytest.raises(ScanBytesExceeded):
            cold.read_raw_rows("prom", 0, None, 0, 2 ** 62, byte_cap=1)
        after = _counters()
        # the refusal came from key metadata alone — zero objects read
        assert after["fetches"] == before["fetches"]


# ---------------------------------------------------------------------------
# Three-tier stitch: raw -> rolled-local -> rolled-cold
# ---------------------------------------------------------------------------


class StitchHarness:
    """Raw + one rolled tier over a TieredColumnStore, router wired the
    way standalone wires it (cold_floor_fn from the AgeOutManager)."""

    RES = 60_000

    def __init__(self, tmp_path):
        self.tiered, self.local, self.cold, self.bucket = _tiered(tmp_path)
        self.meta = DiskMetaStore(str(tmp_path / "meta.db"))
        self.meta.initialize()
        self.ms = TimeSeriesMemStore(self.tiered, self.meta)
        self.ms.setup("prom", DEFAULT_SCHEMAS, 0)
        self.tier_ds = ds_dataset_name("prom", self.RES)
        self.ms.setup(self.tier_ds, DEFAULT_SCHEMAS, 0)
        self.offsets: dict = {}
        self.engine = RollupEngine(node="test")
        self.engine.watch("prom", self.ms, DEFAULT_SCHEMAS,
                          RollupConfig(resolutions_ms=(self.RES,)),
                          {self.RES: self._pub()},
                          column_store=self.tiered,
                          meta_store=self.meta)
        self.mgr = AgeOutManager(self.local, self.cold,
                                 metastore=self.meta)
        self.raw_planner = SingleClusterPlanner(
            "prom", ShardMapper(1), DatasetOptions(), spread_default=0)
        self.tier_planner = SingleClusterPlanner(
            self.tier_ds, ShardMapper(1), DatasetOptions(),
            spread_default=0)

    def _pub(self):
        def pub(shard, container):
            off = self.offsets.get(shard, -1) + 1
            self.offsets[shard] = off
            self.ms.ingest(self.tier_ds, shard, container, off)
        return pub

    def router(self, cold_floor=None):
        return RollupRouterPlanner(
            "prom", self.raw_planner, {self.RES: self.tier_planner},
            rolled_through_fn=lambda r: self.engine.rolled_through(
                "prom", r),
            cold_floor_fn=cold_floor)

    def run_query(self, promql, start, step, end, ms=None,
                  cold_floor=None):
        qctx = QueryContext(sample_limit=10 ** 9)
        plan = query_range_to_logical_plan(promql, start, step, end)
        ep = self.router(cold_floor).materialize(plan, qctx)
        res = ep.execute(ExecContext(ms or self.ms, qctx))
        out = {}
        for b in res.batches:
            vals = b.np_values()
            for i, tags in enumerate(b.keys):
                out[tags.get("inst", "")] = (
                    np.asarray(b.steps.timestamps()).tolist(),
                    [(-1.0 if np.isnan(v) else float(v))
                     for v in vals[i]])
        return out, res, qctx


@pytest.fixture()
def stitch(tmp_path):
    h = StitchHarness(tmp_path)
    rng = np.random.default_rng(23)
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], DatasetOptions())
    # 6h at 30s cadence, 2 series
    ts = T0 + np.arange(0, 6 * 3_600_000, 30_000, dtype=np.int64) + 1
    for i in range(2):
        b.add_series(ts, [rng.normal(5, 1, len(ts))],
                     {"_metric_": "m", "inst": f"i{i}",
                      "_ws_": "w", "_ns_": "n"})
    off = 0
    for c in b.containers():
        h.ms.ingest("prom", 0, c, off)
        off += 1
    h.ms.get_shard("prom", 0).flush_all(ingestion_time=1_000)
    h.engine.run_once("prom")
    h.ms.get_shard(h.tier_ds, 0).flush_all(ingestion_time=2_000)
    return h, int(ts[-1])


class TestThreeTierStitch:
    Q = 'count_over_time(m{_ws_="w",_ns_="n"}[5m])'
    STEP = 300_000

    def test_stitched_bitequal_and_attributed(self, stitch):
        h, last = stitch
        # end one step past the last raw sample: past the tier's closure
        # watermark, so the stitched plan must include a raw leg
        start = T0 + 1_800_000
        end = (last // self.STEP) * self.STEP + self.STEP
        # oracle: everything resident/local, no cold floor
        want, _res, _q = h.run_query(self.Q, start, self.STEP, end)
        # archive rolled rows older than T0+3h, then query through a
        # FRESH memstore so the cold leg truly pages from the bucket
        cutoff = T0 + 3 * 3_600_000
        h.mgr.run(h.tier_ds, int(time.time() * 1000) - cutoff)
        assert h.mgr.floor_ms(h.tier_ds) >= cutoff - 1
        floor = h.mgr.floor_ms
        fresh = TimeSeriesMemStore(h.tiered, h.meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0)
        fresh.setup(h.tier_ds, DEFAULT_SCHEMAS, 0)
        fresh.recover_index("prom", 0)
        fresh.recover_index(h.tier_ds, 0)
        got, res, qctx = h.run_query(
            self.Q, start, self.STEP, end, ms=fresh,
            cold_floor=lambda r: floor(ds_dataset_name("prom", r)))
        assert got == want
        assert set(qctx.rollup_tiers) == {"rolled-cold", "rolled-local",
                                          "raw"}
        assert canonical_tiers(qctx.rollup_tiers) == \
            "rolled-cold+rolled-local+raw"

    def test_cold_only_range_never_scans_raw(self, stitch):
        """A query wholly below the cold floor plans ONE rolled-cold
        leg and reads ZERO raw-dataset rows — the never-scans-raw
        acceptance gate, pinned on the tiered store's read counters."""
        h, last = stitch
        h.mgr.run(h.tier_ds, int(time.time() * 1000) - (last + 1))
        floor = h.mgr.floor_ms
        fresh = TimeSeriesMemStore(h.tiered, h.meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0)
        fresh.setup(h.tier_ds, DEFAULT_SCHEMAS, 0)
        fresh.recover_index("prom", 0)
        fresh.recover_index(h.tier_ds, 0)
        h.tiered.rows_read_by_dataset.clear()
        start = T0 + 1_800_000
        end = T0 + 2 * 3_600_000
        got, res, qctx = h.run_query(
            self.Q, start, self.STEP, end, ms=fresh,
            cold_floor=lambda r: floor(ds_dataset_name("prom", r)))
        assert got  # the archived region still serves
        assert qctx.rollup_tiers == ["rolled-cold"]
        assert h.tiered.rows_read_by_dataset.get("prom", 0) == 0
        assert h.tiered.rows_read_by_dataset.get(h.tier_ds, 0) > 0

    def test_stats_carry_cold_attribution(self, stitch):
        h, last = stitch
        h.mgr.run(h.tier_ds, int(time.time() * 1000) - (last + 1))
        floor = h.mgr.floor_ms
        fresh = TimeSeriesMemStore(h.tiered, h.meta)
        fresh.setup("prom", DEFAULT_SCHEMAS, 0)
        fresh.setup(h.tier_ds, DEFAULT_SCHEMAS, 0)
        fresh.recover_index("prom", 0)
        fresh.recover_index(h.tier_ds, 0)
        _got, res, _qctx = h.run_query(
            self.Q, T0 + 1_800_000, self.STEP, T0 + 2 * 3_600_000,
            ms=fresh,
            cold_floor=lambda r: floor(ds_dataset_name("prom", r)))
        assert res.stats.cold_chunks_paged > 0
        assert res.stats.cold_bytes_read > 0
