"""Cross-node query dispatch: wire serde + HTTP scatter-gather.

Mirrors the reference's serialization round-trip spec and multi-node
query behavior (reference: coordinator/src/test/.../client/
SerializationSpec.scala; multi-jvm cluster query specs) with two real
in-process nodes connected over HTTP sockets."""

import numpy as np
import pytest

from filodb_tpu.coordinator.dispatch import (HttpPlanDispatcher,
                                             dispatcher_factory)
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.ops.windows import StepRange
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.promql.parser import query_range_to_logical_plan
from filodb_tpu.query import wire
from filodb_tpu.query.exec import ExecContext, MultiSchemaPartitionsExec
from filodb_tpu.query.logical import AggregationOperator, RangeFunctionId
from filodb_tpu.query.model import (PeriodicBatch, QueryContext, QueryResult,
                                    RawBatch)
from filodb_tpu.query.transformers import (AggregateMapReduce,
                                           PeriodicSamplesMapper)

BASE = 1_700_000_000_000
STEP = 10_000


class TestWireSerde:
    def test_plan_roundtrip(self):
        plan = MultiSchemaPartitionsExec(
            "prom", 3,
            [ColumnFilter("_metric_", Equals("m")),
             ColumnFilter("host", EqualsRegex("h.*"))],
            BASE, BASE + 600_000, column="count")
        plan.add_transformer(PeriodicSamplesMapper(
            BASE, STEP, BASE + 600_000, window_ms=300_000,
            function=RangeFunctionId.RATE))
        plan.add_transformer(AggregateMapReduce(
            AggregationOperator.SUM, by=("job",)))
        d = wire.serialize_plan(plan)
        import json
        d = json.loads(json.dumps(d))  # must survive real JSON
        plan2 = wire.deserialize_plan(d)
        assert plan2.dataset == "prom" and plan2.shard == 3
        assert plan2.column == "count"
        assert plan2.filters[1].filter.pattern == "h.*"
        assert isinstance(plan2.transformers[0], PeriodicSamplesMapper)
        assert plan2.transformers[0].function == RangeFunctionId.RATE
        assert plan2.transformers[1].by == ("job",)

    def test_result_roundtrip_bit_exact(self):
        rng = np.random.default_rng(0)
        vals = rng.random((3, 10))
        vals[0, 2] = np.nan
        b = PeriodicBatch([{"a": "1"}, {"a": "2"}, {"a": "3"}],
                          StepRange(BASE, BASE + 9 * STEP, STEP), vals)
        res = QueryResult("q1", [b])
        import json
        d = json.loads(json.dumps(wire.serialize_result(res)))
        res2 = wire.deserialize_result(d)
        b2 = res2.batches[0]
        np.testing.assert_array_equal(
            np.asarray(b2.values).view(np.uint64),
            vals.view(np.uint64))  # bit-exact incl. NaN
        assert b2.keys == b.keys
        assert b2.steps == b.steps

    def test_rawbatch_roundtrip(self):
        from filodb_tpu.core.chunk import build_batch
        ts = [np.sort(np.random.default_rng(0).integers(0, 10**6, 20))
              .astype(np.int64) for _ in range(2)]
        vs = [np.random.default_rng(1).random(20) for _ in range(2)]
        batch = build_batch(ts, vs)
        res = QueryResult("q", [RawBatch([{"i": "0"}, {"i": "1"}], batch)])
        res2 = wire.deserialize_result(wire.serialize_result(res))
        b2 = res2.batches[0].batch
        np.testing.assert_array_equal(np.asarray(b2.timestamps),
                                      np.asarray(batch.timestamps))
        np.testing.assert_array_equal(np.asarray(b2.row_counts),
                                      np.asarray(batch.row_counts))

    def test_live_admission_permit_stays_node_local(self):
        # a leaf carrying a live (non-JSON) admission permit must still
        # serialize — the permit is node-local; the remote owner admits
        # the leaf under its own controller (ISSUE 20 regression)
        class _FakePermit:
            released = False
        qctx = QueryContext(query_id="qp", admission_permit=_FakePermit(),
                            batch_key="prom|grid|k")
        plan = MultiSchemaPartitionsExec(
            "prom", 1, [ColumnFilter("_metric_", Equals("m"))],
            BASE, BASE + 600_000, query_context=qctx)
        import json
        d = json.loads(json.dumps(wire.serialize_plan(plan)))
        plan2 = wire.deserialize_plan(d)
        assert plan2.query_context.admission_permit is None
        assert plan2.query_context.batch_key == "prom|grid|k"

    def test_unserializable_plan_raises(self):
        from filodb_tpu.query.exec import EmptyResultExec
        with pytest.raises(wire.WireError):
            wire.serialize_plan(EmptyResultExec())


def _two_node_cluster():
    """Two memstores, each owning half the shards; node-b is served over a
    live HTTP socket and node-a's planner dispatches there."""
    num_shards = 4
    mapper = ShardMapper(num_shards)

    # route records first so node assignment can split the two shards the
    # shard key actually fans out to (spread=1 -> exactly 2 shards)
    rng = np.random.default_rng(5)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    n_series = 8
    for i in range(n_series):
        tags = {"__name__": "dist_total", "instance": f"i{i}",
                "_ws_": "demo", "_ns_": "App-0"}
        ts = BASE + np.arange(300) * STEP
        vals = np.cumsum(rng.random(300))
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    by_shard = {}
    for off, c in enumerate(b.containers()):
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 1) \
                % num_shards
            by_shard.setdefault(shard, []).append((off, rec))
    used = sorted(by_shard)
    assert len(used) == 2, used
    shards_a = [used[0]] + [s for s in range(num_shards) if s not in used]
    shards_b = [used[1]]
    mapper.register_node(shards_a, "node-a")
    mapper.register_node(shards_b, "node-b")
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)

    stores = {"node-a": TimeSeriesMemStore(), "node-b": TimeSeriesMemStore()}
    for ms in stores.values():
        for s in range(num_shards):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
    placed = {"node-a": 0, "node-b": 0}
    for shard, recs in by_shard.items():
        node = mapper.coord_for_shard(shard)
        for off, rec in recs:
            stores[node].get_shard("prom", shard).ingest([rec], off)
            placed[node] += 1
    assert placed["node-a"] and placed["node-b"], placed

    srv_b = FiloHttpServer()
    planner_b = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1)
    srv_b.bind_dataset(DatasetBinding("prom", stores["node-b"], planner_b))
    port_b = srv_b.start()

    endpoints = {"node-b": f"http://127.0.0.1:{port_b}"}
    disp = dispatcher_factory(mapper, endpoints, local_node="node-a")
    planner_a = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1,
                                     dispatcher_for_shard=disp)
    return stores, mapper, planner_a, srv_b


class TestCrossNodeDispatch:
    def test_scatter_gather_across_nodes(self):
        stores, mapper, planner_a, srv_b = _two_node_cluster()
        try:
            plan = query_range_to_logical_plan(
                'sum(rate(dist_total{_ws_="demo",_ns_="App-0"}[2m]))',
                BASE + 600_000, STEP, BASE + 1_200_000)
            ep = planner_a.materialize(plan)
            tree = ep.print_tree()
            res = ep.execute(ExecContext(stores["node-a"], QueryContext()))
            assert res.num_series == 1
            vals = np.asarray(res.batches[0].np_values())[0]
            assert np.isfinite(vals).all()
            # the result must cover ALL series incl. node-b's: a raw
            # selector through the same dispatchers returns every series
            raw_plan = query_range_to_logical_plan(
                'dist_total{_ws_="demo",_ns_="App-0"}',
                BASE + 600_000, STEP, BASE + 1_200_000)
            raw_ep = planner_a.materialize(raw_plan)
            raw_res = raw_ep.execute(ExecContext(stores["node-a"],
                                                 QueryContext()))
            assert raw_res.num_series == 8
        finally:
            srv_b.shutdown()

    def test_remote_error_surfaces_as_query_error(self):
        from filodb_tpu.query.model import QueryError
        d = HttpPlanDispatcher("http://127.0.0.1:9")  # nothing listening
        plan = MultiSchemaPartitionsExec("prom", 0, [], 0, 1)
        with pytest.raises((QueryError, OSError)):
            d.dispatch(plan, ExecContext(TimeSeriesMemStore(),
                                         QueryContext()))

    def test_dispatcher_factory_local_vs_remote(self):
        from filodb_tpu.query.exec import IN_PROCESS
        mapper = ShardMapper(4)
        mapper.register_node([0, 1], "a")
        mapper.register_node([2, 3], "b")
        f = dispatcher_factory(mapper, {"b": "http://x:1"}, local_node="a")
        assert f(0) is IN_PROCESS
        assert isinstance(f(2), HttpPlanDispatcher)
        assert f(2) is f(3)  # cached per node


def test_unknown_owner_fails_not_partial():
    """Regression: a remote-owned shard with no endpoint must fail the
    query instead of silently scanning an empty local store."""
    from filodb_tpu.query.model import QueryError
    mapper = ShardMapper(2)
    mapper.register_node([0], "a")
    mapper.register_node([1], "node-unknown")
    f = dispatcher_factory(mapper, {}, local_node="a")
    d = f(1)
    plan = MultiSchemaPartitionsExec("prom", 1, [], 0, 1)
    with pytest.raises(QueryError, match="no.*endpoint"):
        d.dispatch(plan, ExecContext(TimeSeriesMemStore(), QueryContext()))


def test_metadata_plan_dispatches_over_wire():
    from filodb_tpu.query.exec import LabelValuesExec, PartKeysExec
    lv = LabelValuesExec("prom", 0, ["job"],
                         [ColumnFilter("_metric_", Equals("m"))], 0, 100)
    d = wire.deserialize_plan(wire.serialize_plan(lv))
    assert isinstance(d, LabelValuesExec) and d.label_names == ["job"]
    pk = PartKeysExec("prom", 1, [], 0, 100)
    d2 = wire.deserialize_plan(wire.serialize_plan(pk))
    assert isinstance(d2, PartKeysExec) and d2.shard == 1


def test_query_context_limits_travel():
    plan = MultiSchemaPartitionsExec(
        "prom", 0, [], 0, 1,
        query_context=QueryContext(group_by_cardinality_limit=7,
                                   timeout_ms=1234))
    d = wire.deserialize_plan(wire.serialize_plan(plan))
    assert d.query_context.group_by_cardinality_limit == 7
    assert d.query_context.timeout_ms == 1234
