"""2-node e2e: deadline propagation across the /execplan hop and
partial-results degradation when a data node is down (ISSUE 5).

The remaining wall-clock budget travels the wire as ``budget_ms``
(shrinking at every hop), the data node refuses work that cannot finish
in the budget left, and a scatter-gather whose remote node is dead
degrades to a warned partial result (X-FiloDB-Partial-Data) when the
query opts in — and fails loudly when it does not."""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from filodb_tpu.coordinator.dispatch import (HttpPlanDispatcher,
                                             dispatcher_factory)
from filodb_tpu.coordinator.planner import SingleClusterPlanner
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, DatasetOptions
from filodb_tpu.http.server import DatasetBinding, FiloHttpServer
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.query.model import QueryContext, ShardUnavailable
from filodb_tpu.query.scheduler import QueryScheduler
from filodb_tpu.utils.observability import REGISTRY

BASE = 1_700_000_000_000
STEP = 10_000


def _get(port, path, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{port}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture(scope="module")
def cluster():
    """node-a coordinates; node-b owns one data shard over HTTP.  A
    second coordinator (port_a_dead) routes node-b's shard at a DEAD
    endpoint for the degradation tests."""
    num_shards = 4
    mapper = ShardMapper(num_shards)
    rng = np.random.default_rng(11)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(8):
        tags = {"__name__": "wl2_total", "instance": f"i{i}",
                "_ws_": "demo", "_ns_": "App-0"}
        ts = BASE + np.arange(300) * STEP
        vals = np.cumsum(rng.random(300))
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    by_shard = {}
    for off, c in enumerate(b.containers()):
        for rec in decode_container(c, DEFAULT_SCHEMAS):
            shard = mapper.ingestion_shard(rec.shard_hash, rec.part_hash, 1) \
                % num_shards
            by_shard.setdefault(shard, []).append((off, rec))
    used = sorted(by_shard)
    assert len(used) == 2
    shards_a = [used[0]] + [s for s in range(num_shards) if s not in used]
    shards_b = [used[1]]
    mapper.register_node(shards_a, "node-a")
    mapper.register_node(shards_b, "node-b")
    for s in range(num_shards):
        mapper.update_status(s, ShardStatus.ACTIVE)

    stores = {"node-a": TimeSeriesMemStore(), "node-b": TimeSeriesMemStore()}
    for ms in stores.values():
        for s in range(num_shards):
            ms.setup("prom", DEFAULT_SCHEMAS, s)
    for shard, recs in by_shard.items():
        node = mapper.coord_for_shard(shard)
        for off, rec in recs:
            stores[node].get_shard("prom", shard).ingest([rec], off)

    srv_b = FiloHttpServer()
    planner_b = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1)
    leaf_sched = QueryScheduler(num_workers=2, name="wl2-leaf")
    srv_b.bind_dataset(DatasetBinding("prom", stores["node-b"], planner_b,
                                      leaf_scheduler=leaf_sched))
    port_b = srv_b.start()

    endpoints = {"node-b": f"http://127.0.0.1:{port_b}"}
    disp = dispatcher_factory(mapper, endpoints, local_node="node-a",
                              dispatch_config={"retries": 1,
                                               "backoff-s": 0.01})
    planner_a = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                     spread_default=1,
                                     dispatcher_for_shard=disp)
    srv_a = FiloHttpServer()
    qsched = QueryScheduler(num_workers=2, name="wl2-query")
    srv_a.bind_dataset(DatasetBinding("prom", stores["node-a"], planner_a,
                                      scheduler=qsched))
    port_a = srv_a.start()

    # coordinator with node-b's shard routed at a dead port (nothing
    # listens on it): the degradation / fail-loudly pair
    dead_disp = dispatcher_factory(
        mapper, {"node-b": "http://127.0.0.1:1"}, local_node="node-a",
        dispatch_config={"retries": 1, "backoff-s": 0.01})
    planner_dead = SingleClusterPlanner("prom", mapper, DatasetOptions(),
                                        spread_default=1,
                                        dispatcher_for_shard=dead_disp)
    srv_a_dead = FiloHttpServer()
    srv_a_dead.bind_dataset(DatasetBinding("prom", stores["node-a"],
                                           planner_dead))
    port_a_dead = srv_a_dead.start()

    yield {"port_a": port_a, "port_b": port_b, "port_a_dead": port_a_dead,
           "remote_shard": shards_b[0], "local_shard": shards_a[0],
           "stores": stores, "srv_b": srv_b}
    srv_a.shutdown()
    srv_a_dead.shutdown()
    srv_b.shutdown()
    qsched.shutdown()
    leaf_sched.shutdown()


QUERY = 'sum(rate(wl2_total{_ws_="demo",_ns_="App-0"}[2m]))'


def _query_range(port, **extra):
    return _get(port, "/promql/prom/api/v1/query_range",
                query=QUERY, start=(BASE + 600_000) / 1000,
                end=(BASE + 1_200_000) / 1000, step="30s", **extra)


def _leaf_payload(cluster, budget_ms):
    """An /execplan wire dict for the REMOTE shard carrying an explicit
    remaining budget."""
    from filodb_tpu.core.filters import ColumnFilter, Equals
    from filodb_tpu.query import wire
    from filodb_tpu.query.exec import MultiSchemaPartitionsExec
    plan = MultiSchemaPartitionsExec(
        "prom", cluster["remote_shard"],
        [ColumnFilter("_metric_", Equals("wl2_total"))],
        BASE, BASE + 600_000)
    payload = wire.serialize_plan(plan)
    payload["qctx"]["budget_ms"] = budget_ms
    return payload


def _post_execplan(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/execplan",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestDeadlinePropagation:
    def test_deadline_spans_nodes_end_to_end(self, cluster):
        """A deadlined query fans out over both nodes and succeeds
        while budget remains."""
        code, body, _ = _query_range(cluster["port_a"], timeout="10s",
                                     stats="true")
        assert code == 200 and body["status"] == "success"
        assert len(body["data"]["result"]) == 1
        assert body["data"]["stats"]["samples"]["shardsDown"] == 0

    def test_remote_budget_smaller_than_minted(self, cluster):
        """The hop consumes budget: what the data node would receive is
        strictly less than what the entry minted."""
        from filodb_tpu.query import wire
        from filodb_tpu.workload import deadline as wdl
        qctx = wdl.mint(QueryContext(
            submit_time_ms=int(time.time() * 1000), timeout_ms=5_000))
        time.sleep(0.05)  # planning/queueing happens here in real life
        enc = wire._enc_qctx(qctx)
        assert enc["budget_ms"] < 5_000
        assert enc["budget_ms"] > 0

    def test_remote_refuses_sub_budget_work(self, cluster):
        refused = REGISTRY.counter("filodb_query_deadline_refused_total")
        before = refused.value()
        code, out = _post_execplan(cluster["port_b"],
                                   _leaf_payload(cluster, budget_ms=1))
        assert code == 503
        assert "refusing" in out["error"]
        assert refused.value() == before + 1
        # ample budget: the same work executes fine
        code, out = _post_execplan(cluster["port_b"],
                                   _leaf_payload(cluster, budget_ms=20_000))
        assert code == 200 and out["batches"]

    def test_dispatcher_surfaces_refusal_as_shard_unavailable(self, cluster):
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.query.exec import ExecContext, \
            MultiSchemaPartitionsExec
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        qctx.deadline_ms = int(time.time() * 1000) + 3  # ~nothing left
        plan = MultiSchemaPartitionsExec(
            "prom", cluster["remote_shard"],
            [ColumnFilter("_metric_", Equals("wl2_total"))],
            BASE, BASE + 600_000, query_context=qctx)
        d = HttpPlanDispatcher(f"http://127.0.0.1:{cluster['port_b']}",
                               max_retries=0)
        with pytest.raises(Exception) as exc:
            d.dispatch(plan, ExecContext(cluster["stores"]["node-a"],
                                         qctx))
        # either the node refused (503 -> ShardUnavailable) or the
        # budget died in flight (DeadlineExceeded/timeout) — never a
        # silent 60s hang, never execution
        from filodb_tpu.query.model import QueryError
        assert isinstance(exc.value, (ShardUnavailable, QueryError,
                                      OSError))

    def test_min_budget_runtime_adjustable(self, cluster):
        code, body, _ = _get(cluster["port_b"], "/admin/config",
                             **{"min-remote-budget-ms": "50"})
        assert code == 200
        assert body["data"]["workload"]["min-remote-budget-ms"] == 50
        try:
            code, out = _post_execplan(cluster["port_b"],
                                       _leaf_payload(cluster,
                                                     budget_ms=20))
            assert code == 503  # under the raised floor
        finally:
            _get(cluster["port_b"], "/admin/config",
                 **{"min-remote-budget-ms": "5"})


class TestPartialResults:
    def test_down_node_degrades_with_warning_and_header(self, cluster):
        partial = REGISTRY.counter(
            "filodb_query_partial_shard_results_total")
        before = partial.value()
        code, body, headers = _query_range(
            cluster["port_a_dead"], allow_partial_results="true",
            stats="true")
        assert code == 200 and body["status"] == "success"
        assert body["data"]["result"], \
            "local shard's data must still be served"
        assert any("unreachable" in w for w in body["warnings"])
        assert headers.get("X-FiloDB-Partial-Data") == "true"
        assert body["data"]["stats"]["samples"]["shardsDown"] == 1
        assert partial.value() == before + 1

    def test_without_opt_in_fails_loudly(self, cluster):
        code, body, _ = _query_range(cluster["port_a_dead"])
        assert code == 503
        assert body["status"] == "error"
