"""Smoke runs of the stress drills (short durations) so the harness
itself stays green; full soaks run via ``python -m stress.run_all``.

Reference analog: the stress/ apps are run manually; the multi-jvm
failover specs run in CI (ClusterSingletonFailoverSpec)."""

import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.mark.parametrize("mod,extra", [
    ("stress.ingest_query_stress", ["--seconds", "6", "--series", "200",
                                    "--query-threads", "2"]),
    ("stress.failover_stress", ["--seconds", "12", "--series", "32"]),
])
def test_stress_runner(mod, extra):
    proc = subprocess.run(
        [sys.executable, "-m", mod, *extra], cwd=str(REPO),
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"{mod} failed:\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    assert '"metric"' in proc.stdout
