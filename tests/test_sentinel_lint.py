"""The eight sentinel lints, now thin wrappers over the filolint engine.

Until ISSUE 8 these were 760 lines of ad-hoc AST walking accumulated
one lint per PR; the walking moved to ``filodb_tpu/analysis/``
(doc/analysis.md) and this file keeps two things per lint:

- the full-tree / target-file assertion (the build gate), now phrased
  as "the engine reports zero unsuppressed findings for this rule";
- the original ``*_lint_catches_*`` tests on synthetic snippets, which
  prove the MIGRATION IS BEHAVIOR-PRESERVING: every bad shape the old
  lints caught still fails, every good shape still passes.

The three NEW semantic analyses (lock-discipline, blocking-under-lock,
resource-lifecycle) and the engine itself are covered in
tests/test_analysis.py.
"""

import pathlib

import filodb_tpu.analysis as A

ROOT = pathlib.Path(__file__).resolve().parents[1] / "filodb_tpu"


def _tree(rules):
    """Unsuppressed findings for a rule subset over the whole package."""
    return A.unsuppressed(A.run_paths([ROOT], rules=rules))


def _fake(src, rules, rel="filodb_tpu/fake.py", **kw):
    """Engine run over one synthetic module (catch-tests)."""
    return A.unsuppressed(A.run_source(src, rules=rules, rel=rel, **kw))


def _fmt(findings):
    return "\n  ".join(f"{f.where()}: {f.message}" for f in findings)


# ---------------------------------------------------------------------------
# decode-sentinel (ISSUE 1)
# ---------------------------------------------------------------------------


def test_native_decode_sentinels_are_checked():
    bad = _tree(["decode-sentinel"])
    assert not bad, "native decode sentinel discarded at:\n  " + _fmt(bad)


def test_lint_catches_a_discarded_sentinel():
    """The lint must actually fire on the bad patterns (bare discard,
    assigned-but-unchecked) and accept the checked form."""
    bad = (
        "def f(self, buf):\n"
        "    self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
    )
    got = _fake(bad, ["decode-sentinel"])
    assert len(got) == 1 and "discarded" in got[0].message
    bad2 = (
        "def f(self, buf):\n"
        "    got = self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
        "    return got\n"
    )
    got = _fake(bad2, ["decode-sentinel"])
    assert len(got) == 1 and "never compared" in got[0].message
    good = (
        "def f(self, buf):\n"
        "    got = self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
        "    if got < 0:\n"
        "        raise ValueError('corrupt')\n"
        "    return got\n"
    )
    assert _fake(good, ["decode-sentinel"]) == []


def test_sentinel_suppression_is_engine_syntax():
    """# filolint: disable replaces the legacy # sentinel-ok comment —
    one suppression mechanism for every rule."""
    src = (
        "def f(self, buf):\n"
        "    self._lib.dd_decode(buf, 1, 2, 3, None, 0)  "
        "# filolint: disable=decode-sentinel — bench-only decode, "
        "corruption impossible on the synthetic input\n"
    )
    fs = A.run_source(src, rules=["decode-sentinel"])
    assert A.unsuppressed(fs) == []
    assert any(f.suppressed for f in fs)


# ---------------------------------------------------------------------------
# timed-handler (ISSUE 2)
# ---------------------------------------------------------------------------


def test_route_handlers_record_latency():
    src = (ROOT / "http" / "server.py").read_text()
    assert "class FiloHttpServer" in src       # lint wiring intact
    bad = _tree(["timed-handler"])
    assert not bad, "dark HTTP endpoints:\n  " + _fmt(bad)


def test_route_lint_catches_dark_endpoint():
    fake = (
        "class FiloHttpServer:\n"
        "    def _route(self, path, params, multi=None):\n"
        "        return self._dark(params)\n"
        "    def _dark(self, p):\n"
        "        return 200, {}\n"
    )
    got = _fake(fake, ["timed-handler"])
    assert len(got) == 1 and "_dark" in got[0].message
    timed = (
        "class FiloHttpServer:\n"
        "    def _route(self, path, params, multi=None):\n"
        "        return self._lit(params)\n"
        "    @_timed('lit')\n"
        "    def _lit(self, p):\n"
        "        return 200, {}\n"
    )
    assert _fake(timed, ["timed-handler"]) == []


# ---------------------------------------------------------------------------
# interpret-coverage (ISSUE 3)
# ---------------------------------------------------------------------------


def test_ops_kernel_entry_points_have_interpret_tests():
    modules, root = A.load_modules([ROOT])
    project = A.Project(modules, root)
    from filodb_tpu.analysis.sentinels import kernel_entry_points
    assert kernel_entry_points(project), \
        "no kernel entry points found — lint wiring broken?"
    bad = _tree(["interpret-coverage"])
    assert not bad, \
        "kernels without interpret coverage:\n  " + _fmt(bad)


def test_interpret_lint_catches_uncovered_kernel():
    src = "def totally_new_kernel(x, interpret=False):\n    return x\n"
    got = _fake(src, ["interpret-coverage"],
                rel="filodb_tpu/ops/fake.py", test_sources=["x = 1"])
    assert len(got) == 1 and "totally_new_kernel" in got[0].message
    covered = _fake(
        src, ["interpret-coverage"], rel="filodb_tpu/ops/fake.py",
        test_sources=["out = totally_new_kernel(a, interpret=True)"])
    assert covered == []


# ---------------------------------------------------------------------------
# device-put-ledger (ISSUE 4)
# ---------------------------------------------------------------------------


def test_device_put_routes_through_ledger():
    bad = _tree(["device-put-ledger"])
    assert not bad, "unledgered device_put at:\n  " + _fmt(bad)


def test_device_put_lint_catches_raw_call():
    attr = "import jax\nx = jax.device_put(a, d)\n"
    assert len(_fake(attr, ["device-put-ledger"])) == 1
    bare = "from jax import device_put\nx = device_put(a, d)\n"
    assert len(_fake(bare, ["device-put-ledger"])) == 1
    ok = ("from filodb_tpu.utils.devicewatch import LEDGER\n"
          "x = LEDGER.device_put(a, d, owner='o', fmt='dense')\n")
    assert _fake(ok, ["device-put-ledger"]) == []
    # the wrapper module itself is the one allowed raw call site
    assert _fake(attr, ["device-put-ledger"],
                 rel="filodb_tpu/utils/devicewatch.py") == []


# ---------------------------------------------------------------------------
# admission-routing (ISSUE 5)
# ---------------------------------------------------------------------------


def test_query_handlers_route_through_admission():
    src = (ROOT / "http" / "server.py").read_text()
    assert "class FiloHttpServer" in src       # lint wiring intact
    bad = _tree(["admission-routing"])
    assert not bad, "admission bypass:\n  " + _fmt(bad)


def test_admission_lint_catches_bypass():
    bypass = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        with self._admit(b, plan, q):\n"
        "            pass\n"
        "    def _sneaky(self, b, p):\n"
        "        ep = b.planner.materialize(p, q)\n"
        "        return 200, {}\n"
    )
    got = _fake(bypass, ["admission-routing"])
    assert len(got) == 1 and "_sneaky" in got[0].message
    no_admit = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        ep = b.planner.materialize(plan, q)\n"
        "        return ep.execute(ctx)\n"
    )
    got = _fake(no_admit, ["admission-routing"])
    assert len(got) == 1 and "_admit" in got[0].message
    ok = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        ep = b.planner.materialize(plan, q)\n"
        "        with self._admit(b, ep, q):\n"
        "            return ep.execute(ctx)\n"
    )
    assert _fake(ok, ["admission-routing"]) == []


# ---------------------------------------------------------------------------
# deadline-threading (ISSUE 5)
# ---------------------------------------------------------------------------


def test_remote_dispatch_threads_deadline():
    bad = _tree(["deadline-threading"])
    assert not bad, "unthreaded deadlines:\n  " + _fmt(bad)


def test_deadline_lint_catches_fixed_timeout():
    fixed = (
        "import urllib.request\n"
        "class MyPlanDispatcher:\n"
        "    def dispatch(self):\n"
        "        urllib.request.urlopen(req, timeout=60.0)\n"
    )
    got = _fake(fixed, ["deadline-threading"])
    assert len(got) == 1 and "thread the deadline" in got[0].message
    missing = (
        "import urllib.request\n"
        "def poll():\n"
        "    urllib.request.urlopen(url)\n"
    )
    got = _fake(missing, ["deadline-threading"])
    assert len(got) == 1 and "without" in got[0].message
    ok = (
        "import urllib.request\n"
        "class MyPlanDispatcher:\n"
        "    def dispatch(self):\n"
        "        deadline_timeout_s = dl.budget_timeout_s(q, 60.0)\n"
        "        urllib.request.urlopen(req, timeout=deadline_timeout_s)\n"
    )
    assert _fake(ok, ["deadline-threading"]) == []
    plain_ok = (
        "import urllib.request\n"
        "def poll():\n"
        "    urllib.request.urlopen(url, timeout=5)\n"
    )
    assert _fake(plain_ok, ["deadline-threading"]) == []


def test_deadline_lint_covers_cold_bucket_fetches():
    """ISSUE 16: every cold-bucket ``get_object`` call-site outside the
    bucket implementations must carry a ``timeout_s`` derived from the
    remaining deadline/admin budget — a stalled bucket must become a
    deadline refusal, never a wedged worker."""
    missing = (
        "def fetch(self, key):\n"
        "    return self.bucket.get_object(key)\n"
    )
    got = _fake(missing, ["deadline-threading"])
    assert len(got) == 1 and "without timeout_s" in got[0].message
    fixed = (
        "def fetch(self, key):\n"
        "    return self.bucket.get_object(key, timeout_s=30.0)\n"
    )
    got = _fake(fixed, ["deadline-threading"])
    assert len(got) == 1 and "thread the deadline" in got[0].message
    ok = (
        "def fetch(self, key):\n"
        "    deadline_timeout_s = self._fetch_timeout_s()\n"
        "    return self.bucket.get_object(key,\n"
        "                                  timeout_s=deadline_timeout_s)\n"
    )
    assert _fake(ok, ["deadline-threading"]) == []
    # the bucket IMPLEMENTATION defines get_object and may call through
    # to a wrapped delegate without re-deriving the budget
    impl = (
        "def get_object(self, key, *, timeout_s):\n"
        "    return self.inner.get_object(key, timeout_s=timeout_s)\n"
    )
    assert _fake(impl, ["deadline-threading"],
                 rel="filodb_tpu/coldstore/bucket.py") == []


# ---------------------------------------------------------------------------
# metric-doc (ISSUE 6)
# ---------------------------------------------------------------------------


def test_metric_families_are_documented():
    modules, root = A.load_modules([ROOT])
    project = A.Project(modules, root)
    from filodb_tpu.analysis.sentinels import registered_metric_names
    assert registered_metric_names(project), \
        "no registered filodb_* metrics found — lint broken?"
    bad = _tree(["metric-doc"])
    assert not bad, "undocumented metrics:\n  " + _fmt(bad)


def test_metric_doc_lint_catches_drift():
    doc = ("| `filodb_query_*` | `request_seconds`, `requests_total` |\n"
           "`filodb_node_up` is set at startup.\n")

    def check(name, doc_text):
        src = f"m = REG.counter({name!r}, 'h')\n"
        return _fake(src, ["metric-doc"], doc_text=doc_text)

    assert check("filodb_query_request_seconds", doc) == []
    assert check("filodb_node_up", doc) == []
    bad = check("filodb_query_brand_new_total", doc)
    assert len(bad) == 1 and "filodb_query_brand_new_total" in bad[0].message
    assert len(check("filodb_sneaky_family_total", doc)) == 1
    # a suffix documented under a DIFFERENT family's row must not cover
    # this family (same-line rule)
    doc2 = ("| `filodb_flush_*` | `failures_total` |\n"
            "| `filodb_odp_*` | `pagein_seconds` |\n")
    bad = check("filodb_odp_failures_total", doc2)
    assert len(bad) == 1 and "filodb_odp_failures_total" in bad[0].message


# ---------------------------------------------------------------------------
# replica-routing (ISSUE 7)
# ---------------------------------------------------------------------------


def test_replica_routing_goes_through_pick():
    bad = _tree(["replica-routing"])
    assert not bad, "ad-hoc replica routing:\n  " + _fmt(bad)


def test_replica_routing_lint_catches_ad_hoc_lists():
    bad_enum = (
        "class MyPlanDispatcher:\n"
        "    def dispatch(self, plan, ctx):\n"
        "        node = self.mapper.replica_nodes(plan.shard)[0]\n"
        "        return node\n"
    )
    got = _fake(bad_enum, ["replica-routing"])
    assert len(got) == 1 and "ReplicaSet.pick" in got[0].message
    bad_failover = (
        "def failover_target(shard, nodes):\n"
        "    return sorted(nodes)[0]\n"
    )
    got = _fake(bad_failover, ["replica-routing"])
    assert len(got) == 1 and "failover_target" in got[0].message
    ok = (
        "class MyPlanDispatcher:\n"
        "    def dispatch(self, plan, ctx):\n"
        "        for node in self.replica_set.pick(self.shard):\n"
        "            return node\n"
        "def hedge_alternate_for(plan, this_node):\n"
        "    return rs.alternate(plan.shard, exclude=[this_node])\n"
    )
    assert _fake(ok, ["replica-routing"]) == []
    # and the policy home itself is exempt
    assert _fake(bad_enum, ["replica-routing"],
                 rel="filodb_tpu/coordinator/replicas.py") == []
