"""Lint: native decode -1 sentinels must never be silently discarded.

Every native decode entry point reports corruption through an in-band
sentinel (-1 / None / False) instead of raising.  ISSUE 1's tentpole
turns those sentinels into structured CorruptVectorError diagnoses —
this AST lint keeps FUTURE call-sites honest: a call whose sentinel
return is discarded (bare expression statement) or assigned but never
compared/branched on in the same function fails the build, unless the
line carries an explicit ``# sentinel-ok: <reason>`` suppression.

Two classes of call-site are linted:
- raw ctypes calls (``self._lib.<fn>`` / ``lib.<fn>``) to functions
  whose C return is a -1 sentinel;
- adapter-protocol methods that RETURN sentinels instead of raising
  (``nb.page_decode`` -> None, ``npr.gather`` -> None, ...).
"""

import ast
import pathlib

ROOT = pathlib.Path(__file__).resolve().parents[1] / "filodb_tpu"

# raw C functions with a -1 (or negative) corruption/overflow sentinel
RAW_SENTINEL_FNS = {
    "np_unpack", "np_packed_end", "dd_decode", "xor_unpack",
    "ll_encode_batch", "dbl_encode_batch", "ll_decode_batch",
    "dbl_decode_batch", "page_decode_column", "influx_parse_batch",
    "gather_ranges", "head_hash128", "verify_heads",
}
# adapter methods returning None/False/INVALID sentinels; keyed by the
# receiver names they are conventionally bound to (keeps generic names
# like `gather` from matching unrelated code)
ADAPTER_SENTINEL_FNS = {
    "page_decode": {"nb"},
    "page_decode_into": {"nb"},
    "gather": {"npr"},
    "head_hashes": {"npr"},
    "verify": {"npr"},
    "parse": {"npr", "nparse"},
}


def _receiver_name(func: ast.expr):
    """For a Call func like a.b.c(...), the names involved."""
    if not isinstance(func, ast.Attribute):
        return None, None
    attr = func.attr
    v = func.value
    if isinstance(v, ast.Name):
        return attr, v.id
    if isinstance(v, ast.Attribute):
        return attr, v.attr
    return attr, None


def _is_sentinel_call(node: ast.Call):
    attr, recv = _receiver_name(node.func)
    if attr is None:
        return False
    if attr in RAW_SENTINEL_FNS and recv in ("_lib", "lib"):
        return True
    if attr in ADAPTER_SENTINEL_FNS and recv in ADAPTER_SENTINEL_FNS[attr]:
        return True
    return False


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _guard_names(func_node) -> set:
    """Names used anywhere in the function inside a comparison, boolean
    test, or branch condition — i.e. names whose value IS checked."""
    used = set()
    for n in ast.walk(func_node):
        if isinstance(n, ast.Compare):
            used |= _names_in(n)
        elif isinstance(n, (ast.If, ast.While, ast.IfExp)):
            used |= _names_in(n.test)
        elif isinstance(n, ast.Assert):
            used |= _names_in(n.test)
        elif isinstance(n, ast.BoolOp):
            used |= _names_in(n)
        elif isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not):
            used |= _names_in(n)
    return used


def _check_function(func_node, src_lines, path, violations):
    guards = _guard_names(func_node)
    for stmt in ast.walk(func_node):
        if not isinstance(stmt, ast.stmt):
            continue
        calls = [n for n in ast.walk(stmt)
                 if isinstance(n, ast.Call) and _is_sentinel_call(n)]
        # only handle calls whose NEAREST enclosing statement is stmt
        # (avoid double-reporting through nested statements)
        for call in calls:
            inner = [s for s in ast.walk(stmt)
                     if isinstance(s, ast.stmt) and s is not stmt
                     and call in ast.walk(s)]
            if inner:
                continue
            line = src_lines[call.lineno - 1]
            if "# sentinel-ok" in line:
                continue
            where = f"{path.relative_to(ROOT.parent)}:{call.lineno}"
            attr, _ = _receiver_name(call.func)
            if isinstance(stmt, (ast.If, ast.While)) and \
                    call in ast.walk(stmt.test):
                continue                      # branched on directly
            if isinstance(stmt, (ast.Raise, ast.Assert)):
                continue                      # raising with it
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                names = set()
                for t in targets:
                    names |= _names_in(t)
                if names & guards:
                    continue                  # assigned, then checked
                violations.append(
                    f"{where}: result of {attr}() assigned to "
                    f"{sorted(names)} but never compared/branched on in "
                    f"this function — a -1 sentinel would be silently "
                    f"discarded")
                continue
            if isinstance(stmt, ast.Return) and isinstance(
                    stmt.value, (ast.IfExp, ast.Compare, ast.BoolOp)):
                continue                      # returns a checked form
            violations.append(
                f"{where}: result of {attr}() is discarded without "
                f"raising or counting (bare use); check the sentinel or "
                f"annotate '# sentinel-ok: <reason>'")


def test_native_decode_sentinels_are_checked():
    violations = []
    for path in sorted(ROOT.rglob("*.py")):
        src = path.read_text()
        try:
            tree = ast.parse(src)
        except SyntaxError as e:  # pragma: no cover - broken file
            violations.append(f"{path}: unparseable: {e}")
            continue
        src_lines = src.splitlines()
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            _check_function(fn, src_lines, path, violations)
    assert not violations, \
        "native decode sentinel discarded at:\n  " + "\n  ".join(violations)


# ---------------------------------------------------------------------------
# HTTP route-handler latency lint (ISSUE 2): every handler the server's
# _route dispatches to must wear the @_timed decorator, so no endpoint
# added later can be dark on the request histogram.
# ---------------------------------------------------------------------------


def _route_handlers(tree):
    """(class node, handler method names called as ``return self._x(...)``
    inside FiloHttpServer._route)."""
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "FiloHttpServer"):
            continue
        for fn in cls.body:
            if isinstance(fn, ast.FunctionDef) and fn.name == "_route":
                names = set()
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Return) or node.value is None:
                        continue
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and isinstance(c.func.value, ast.Name) \
                                and c.func.value.id == "self":
                            names.add(c.func.attr)
                return cls, names
    return None, set()


def _untimed_handlers(src: str) -> list:
    tree = ast.parse(src)
    cls, names = _route_handlers(tree)
    if cls is None:
        return ["FiloHttpServer._route not found"]
    bad = []
    for fn in cls.body:
        if not (isinstance(fn, ast.FunctionDef) and fn.name in names):
            continue
        decorated = False
        for d in fn.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            if isinstance(target, ast.Name) and target.id == "_timed":
                decorated = True
        if not decorated:
            bad.append(f"{fn.name} (line {fn.lineno}): dispatched from "
                       f"_route but not decorated with @_timed — its "
                       f"latency never reaches the request histogram")
    return bad


def test_route_handlers_record_latency():
    src = (ROOT / "http" / "server.py").read_text()
    bad = _untimed_handlers(src)
    assert not bad, "dark HTTP endpoints:\n  " + "\n  ".join(bad)


def test_route_lint_catches_dark_endpoint():
    """The route lint must actually fire on an undecorated handler."""
    fake = (
        "class FiloHttpServer:\n"
        "    def _route(self, path, params, multi=None):\n"
        "        return self._dark(params)\n"
        "    def _dark(self, p):\n"
        "        return 200, {}\n"
    )
    bad = _untimed_handlers(fake)
    assert len(bad) == 1 and "_dark" in bad[0]
    timed = (
        "class FiloHttpServer:\n"
        "    def _route(self, path, params, multi=None):\n"
        "        return self._lit(params)\n"
        "    @_timed('lit')\n"
        "    def _lit(self, p):\n"
        "        return 200, {}\n"
    )
    assert _untimed_handlers(timed) == []


def test_lint_catches_a_discarded_sentinel():
    """The lint itself must actually fire on the bad pattern."""
    bad = (
        "def f(self, buf):\n"
        "    self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
    )
    violations = []
    tree = ast.parse(bad)
    _check_function(tree.body[0], bad.splitlines(),
                    ROOT / "fake.py", violations)
    assert len(violations) == 1
    bad2 = (
        "def f(self, buf):\n"
        "    got = self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
        "    return got\n"
    )
    violations = []
    tree = ast.parse(bad2)
    _check_function(tree.body[0], bad2.splitlines(),
                    ROOT / "fake.py", violations)
    assert len(violations) == 1
    good = (
        "def f(self, buf):\n"
        "    got = self._lib.dd_decode(buf, 1, 2, 3, None, 0)\n"
        "    if got < 0:\n"
        "        raise ValueError('corrupt')\n"
        "    return got\n"
    )
    violations = []
    tree = ast.parse(good)
    _check_function(tree.body[0], good.splitlines(),
                    ROOT / "fake.py", violations)
    assert violations == []


# ---------------------------------------------------------------------------
# Kernel interpret-coverage lint (ISSUE 3): every jitted Pallas kernel
# entry point in filodb_tpu/ops/ (identified by its ``interpret``
# parameter — the convention every pallas wrapper follows) must have an
# interpret-mode test referencing it, so CPU CI exercises the kernel
# body even though Mosaic only compiles on TPU.  A new kernel without
# an interpret test fails the build here.
# ---------------------------------------------------------------------------

TESTS_DIR = pathlib.Path(__file__).resolve().parent


def _kernel_entry_points(ops_dir=None):
    """Top-level public functions in ops/*.py taking ``interpret``."""
    ops_dir = ops_dir or (ROOT / "ops")
    out = []
    for path in sorted(ops_dir.glob("*.py")):
        tree = ast.parse(path.read_text())
        for fn in tree.body:
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name.startswith("_"):
                continue
            args = fn.args
            names = [a.arg for a in args.args + args.kwonlyargs]
            if "interpret" in names:
                out.append((path.name, fn.name))
    return out


def _uncovered_kernels(entry_points, test_sources):
    """Entry points with no test file that BOTH calls them and runs in
    interpret mode."""
    missing = []
    for fname, fn in entry_points:
        covered = any(fn + "(" in src and "interpret=True" in src
                      for src in test_sources)
        if not covered:
            missing.append(f"{fname}:{fn} has no interpret-mode test "
                           f"(call it with interpret=True in tests/)")
    return missing


def test_ops_kernel_entry_points_have_interpret_tests():
    eps = _kernel_entry_points()
    assert eps, "no kernel entry points found — lint wiring broken?"
    srcs = [p.read_text() for p in TESTS_DIR.glob("test_*.py")]
    missing = _uncovered_kernels(eps, srcs)
    assert not missing, \
        "kernels without interpret coverage:\n  " + "\n  ".join(missing)


def test_interpret_lint_catches_uncovered_kernel():
    """The lint must actually fire on an uncovered entry point."""
    missing = _uncovered_kernels([("fake.py", "totally_new_kernel")],
                                 ["x = 1"])
    assert len(missing) == 1 and "totally_new_kernel" in missing[0]
    covered = _uncovered_kernels(
        [("fake.py", "totally_new_kernel")],
        ["out = totally_new_kernel(a, interpret=True)"])
    assert covered == []


# ---------------------------------------------------------------------------
# HBM-ledger lint (ISSUE 4): every ``jax.device_put`` under filodb_tpu/
# must route through the devicewatch residency ledger
# (LEDGER.device_put / a local wrapper built on it), so every byte that
# lands on the accelerator is attributed to an owner — a raw call would
# be invisible to /admin/device and break the reconciliation invariant.
# The wrapper module itself is the only allowed raw call site.
# ---------------------------------------------------------------------------

DEVICE_PUT_ALLOWLIST = {"utils/devicewatch.py"}


def _raw_device_put_calls(src: str, relpath: str) -> list:
    """Raw ``jax.device_put(...)`` (or bare ``device_put(...)`` imported
    from jax) call sites in one module."""
    tree = ast.parse(src)
    # names `device_put` was imported under (from jax import device_put)
    imported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[0] == "jax":
            for alias in node.names:
                if alias.name == "device_put":
                    imported.add(alias.asname or alias.name)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        raw = (isinstance(f, ast.Attribute) and f.attr == "device_put"
               and isinstance(f.value, ast.Name) and f.value.id == "jax") \
            or (isinstance(f, ast.Name) and f.id in imported)
        if raw:
            out.append(f"{relpath}:{node.lineno}: raw jax.device_put — "
                       f"route it through devicewatch LEDGER.device_put"
                       f"(..., owner=..., fmt=...) so the bytes are "
                       f"attributed on the HBM residency ledger")
    return out


def test_device_put_routes_through_ledger():
    violations = []
    for path in sorted(ROOT.rglob("*.py")):
        rel = str(path.relative_to(ROOT))
        if rel in DEVICE_PUT_ALLOWLIST:
            continue
        violations.extend(_raw_device_put_calls(path.read_text(), rel))
    assert not violations, \
        "unledgered device_put at:\n  " + "\n  ".join(violations)


def test_device_put_lint_catches_raw_call():
    """The ledger lint must actually fire on both raw spellings."""
    attr = "import jax\nx = jax.device_put(a, d)\n"
    assert len(_raw_device_put_calls(attr, "fake.py")) == 1
    bare = "from jax import device_put\nx = device_put(a, d)\n"
    assert len(_raw_device_put_calls(bare, "fake.py")) == 1
    ok = ("from filodb_tpu.utils.devicewatch import LEDGER\n"
          "x = LEDGER.device_put(a, d, owner='o', fmt='dense')\n")
    assert _raw_device_put_calls(ok, "fake.py") == []


# ---------------------------------------------------------------------------
# Admission-routing lint (ISSUE 5): every HTTP query handler must reach
# execution through the admission controller.  Concretely: inside
# FiloHttpServer, ONLY ``_exec`` may materialize a plan (handlers call
# self._exec, which prices + admits before scheduling), and ``_exec``
# itself must call ``self._admit``.  A future handler that plans or
# executes directly would bypass the overload defense — it fails here.
# ---------------------------------------------------------------------------


def _admission_violations(src: str) -> list:
    tree = ast.parse(src)
    out = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name == "FiloHttpServer"):
            continue
        exec_has_admit = False
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                if node.func.attr == "materialize" and fn.name != "_exec":
                    out.append(
                        f"{fn.name} (line {node.lineno}): materializes a "
                        f"plan outside _exec — queries must route through "
                        f"self._exec so admission control prices and "
                        f"admits them")
                if fn.name == "_exec" and node.func.attr == "_admit" \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self":
                    exec_has_admit = True
        if not exec_has_admit:
            out.append("_exec does not call self._admit — the admission "
                       "front door is disconnected")
        return out
    return ["FiloHttpServer not found"]


def test_query_handlers_route_through_admission():
    src = (ROOT / "http" / "server.py").read_text()
    bad = _admission_violations(src)
    assert not bad, "admission bypass:\n  " + "\n  ".join(bad)


def test_admission_lint_catches_bypass():
    """The admission lint must fire on a handler that plans directly
    and on an _exec with no admission call."""
    bypass = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        with self._admit(b, plan, q):\n"
        "            pass\n"
        "    def _sneaky(self, b, p):\n"
        "        ep = b.planner.materialize(p, q)\n"
        "        return 200, {}\n"
    )
    bad = _admission_violations(bypass)
    assert len(bad) == 1 and "_sneaky" in bad[0]
    no_admit = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        ep = b.planner.materialize(plan, q)\n"
        "        return ep.execute(ctx)\n"
    )
    bad = _admission_violations(no_admit)
    assert len(bad) == 1 and "_admit" in bad[0]
    ok = (
        "class FiloHttpServer:\n"
        "    def _exec(self, b, plan):\n"
        "        ep = b.planner.materialize(plan, q)\n"
        "        with self._admit(b, ep, q):\n"
        "            return ep.execute(ctx)\n"
    )
    assert _admission_violations(ok) == []


# ---------------------------------------------------------------------------
# Deadline-threading lint (ISSUE 5): every remote dispatch call site
# must thread the query's deadline.  Two tiers:
# - EVERY ``urlopen`` under filodb_tpu/ must pass an explicit
#   ``timeout=`` (an unbounded socket can pin a worker forever);
# - inside dispatcher/exec classes (class name ending in Dispatcher or
#   Exec — the remote QUERY call sites), the timeout expression must
#   reference the remaining deadline budget (a name mentioning
#   deadline/remaining/budget), not a fixed constant.
# ---------------------------------------------------------------------------

_DEADLINE_NAMES = ("deadline", "remaining", "budget")


def _deadline_violations(src: str, relpath: str) -> list:
    tree = ast.parse(src)
    out = []

    def names_in(expr) -> set:
        got = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                got.add(n.id)
            elif isinstance(n, ast.Attribute):
                got.add(n.attr)
        return got

    def check_call(node, in_dispatch_class):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, (ast.Attribute, ast.Name))):
            return
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id
        if fname != "urlopen":
            return
        timeout_kw = next((k for k in node.keywords
                           if k.arg == "timeout"), None)
        if timeout_kw is None:
            out.append(f"{relpath}:{node.lineno}: urlopen without "
                       f"timeout= — an unbounded socket can pin a "
                       f"worker forever")
            return
        if in_dispatch_class:
            refs = {n.lower() for n in names_in(timeout_kw.value)}
            if not any(dn in r for dn in _DEADLINE_NAMES for r in refs):
                out.append(
                    f"{relpath}:{node.lineno}: remote dispatch urlopen "
                    f"whose timeout does not thread the deadline — "
                    f"derive it from the remaining budget "
                    f"(workload/deadline.py budget_timeout_s)")

    dispatch_nodes = set()
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and (
                cls.name.endswith("Dispatcher")
                or cls.name.endswith("Exec")):
            for n in ast.walk(cls):
                dispatch_nodes.add(id(n))
    for node in ast.walk(tree):
        check_call(node, id(node) in dispatch_nodes)
    return out


def test_remote_dispatch_threads_deadline():
    violations = []
    for path in sorted(ROOT.rglob("*.py")):
        rel = str(path.relative_to(ROOT))
        violations.extend(_deadline_violations(path.read_text(), rel))
    assert not violations, \
        "unthreaded deadlines:\n  " + "\n  ".join(violations)


def test_deadline_lint_catches_fixed_timeout():
    """The deadline lint must fire on a fixed dispatch timeout and on
    a missing timeout, and accept a budget-derived one."""
    fixed = (
        "import urllib.request\n"
        "class MyPlanDispatcher:\n"
        "    def dispatch(self):\n"
        "        urllib.request.urlopen(req, timeout=60.0)\n"
    )
    bad = _deadline_violations(fixed, "fake.py")
    assert len(bad) == 1 and "thread the deadline" in bad[0]
    missing = (
        "import urllib.request\n"
        "def poll():\n"
        "    urllib.request.urlopen(url)\n"
    )
    bad = _deadline_violations(missing, "fake.py")
    assert len(bad) == 1 and "without" in bad[0]
    ok = (
        "import urllib.request\n"
        "class MyPlanDispatcher:\n"
        "    def dispatch(self):\n"
        "        deadline_timeout_s = dl.budget_timeout_s(q, 60.0)\n"
        "        urllib.request.urlopen(req, timeout=deadline_timeout_s)\n"
    )
    assert _deadline_violations(ok, "fake.py") == []
    plain_ok = (
        "import urllib.request\n"
        "def poll():\n"
        "    urllib.request.urlopen(url, timeout=5)\n"
    )
    assert _deadline_violations(plain_ok, "fake.py") == []


# ---------------------------------------------------------------------------
# Metric/doc drift lint (ISSUE 6): every `filodb_*` metric family
# registered anywhere under filodb_tpu/ must appear in
# doc/observability.md's metric table.  A name is documented when it
# appears verbatim, OR when a family row (`filodb_<fam>_*`) covers its
# prefix AND the remaining suffix appears in the doc (the table's
# shorthand: family column + per-metric suffixes).  Metrics that creep
# in undocumented — the drift PRs 6-10 accumulated — fail the build.
# ---------------------------------------------------------------------------

_METRIC_CTORS = {"counter", "gauge", "histogram"}
DOC_OBS = ROOT.parent / "doc" / "observability.md"


def _registered_metric_names(root=None) -> set:
    """Every string-literal filodb_* name passed to a registry
    counter()/gauge()/histogram() call under filodb_tpu/."""
    root = root or ROOT
    names = set()
    for path in sorted(root.rglob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_CTORS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if name.startswith("filodb_"):
                names.add(name)
    return names


def _undocumented_metrics(names, doc_text: str) -> list:
    doc_lines = doc_text.splitlines()
    missing = []
    for name in sorted(names):
        if name in doc_text:
            continue
        parts = name.split("_")
        covered = False
        # try every family split: filodb_query_* + "request_seconds",
        # filodb_query_request_* + "seconds", ... — the suffix must sit
        # on the SAME line (table row) as the family pattern, or a
        # suffix shared with another family would mask the drift
        for i in range(2, len(parts)):
            fam = "_".join(parts[:i]) + "_*"
            suffix = "_".join(parts[i:])
            if any(fam in line and suffix in line for line in doc_lines):
                covered = True
                break
        if not covered:
            missing.append(
                f"{name}: not in doc/observability.md's metric table — "
                f"add the full name, or list its suffix on a "
                f"`filodb_<family>_*` row")
    return missing


def test_metric_families_are_documented():
    names = _registered_metric_names()
    assert names, "no registered filodb_* metrics found — lint broken?"
    missing = _undocumented_metrics(names, DOC_OBS.read_text())
    assert not missing, \
        "undocumented metrics:\n  " + "\n  ".join(missing)


def test_metric_doc_lint_catches_drift():
    """The doc lint must fire on an undocumented name and accept both
    documented spellings."""
    doc = ("| `filodb_query_*` | `request_seconds`, `requests_total` |\n"
           "`filodb_node_up` is set at startup.\n")
    assert _undocumented_metrics({"filodb_query_request_seconds"}, doc) == []
    assert _undocumented_metrics({"filodb_node_up"}, doc) == []
    bad = _undocumented_metrics({"filodb_query_brand_new_total"}, doc)
    assert len(bad) == 1 and "filodb_query_brand_new_total" in bad[0]
    bad = _undocumented_metrics({"filodb_sneaky_family_total"}, doc)
    assert len(bad) == 1
    # a suffix documented under a DIFFERENT family's row must not cover
    # this family (same-line rule)
    doc2 = ("| `filodb_flush_*` | `failures_total` |\n"
            "| `filodb_odp_*` | `pagein_seconds` |\n")
    bad = _undocumented_metrics({"filodb_odp_failures_total"}, doc2)
    assert len(bad) == 1 and "filodb_odp_failures_total" in bad[0]


# ---------------------------------------------------------------------------
# Replica-routing lint (ISSUE 7): every dispatcher site that targets,
# retargets, hedges, or fails over a leaf selects its replica through
# the SINGLE ReplicaSet.pick()/alternate() routing helper
# (coordinator/replicas.py).  Ad-hoc node lists inside dispatcher
# classes — enumerating mapper replicas and ordering them locally —
# fork the routing policy and rot independently.
# ---------------------------------------------------------------------------

_REPLICA_ENUMERATORS = {"replicas", "replica_nodes", "live_replicas"}
_ROUTING_FN_HINTS = ("failover", "retarget", "hedge_alternate")
_ROUTING_HELPERS = {"pick", "alternate"}


def _replica_routing_violations(src: str, relpath: str) -> list:
    if relpath.endswith("coordinator/replicas.py"):
        return []            # the policy's one home
    tree = ast.parse(src)
    out = []

    def called_attrs(node) -> set:
        got = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute):
                got.add(n.func.attr)
        return got

    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef)
                and cls.name.endswith("Dispatcher")):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            bad = called_attrs(fn) & _REPLICA_ENUMERATORS
            if bad:
                out.append(
                    f"{relpath}:{fn.lineno}: {cls.name}.{fn.name} "
                    f"enumerates replicas ad hoc ({sorted(bad)}) — "
                    f"dispatchers must select through ReplicaSet.pick()")
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not any(h in fn.name for h in _ROUTING_FN_HINTS):
            continue
        if not (called_attrs(fn) & _ROUTING_HELPERS):
            out.append(
                f"{relpath}:{fn.lineno}: routing site {fn.name}() does "
                f"not go through ReplicaSet.pick()/alternate()")
    return out


def test_replica_routing_goes_through_pick():
    violations = []
    for path in sorted(ROOT.rglob("*.py")):
        rel = str(path.relative_to(ROOT))
        violations.extend(
            _replica_routing_violations(path.read_text(), rel))
    assert not violations, \
        "ad-hoc replica routing:\n  " + "\n  ".join(violations)


def test_replica_routing_lint_catches_ad_hoc_lists():
    """The routing lint must fire on a dispatcher enumerating replicas
    itself and on a pick-less failover helper, and accept the
    pick-routed shapes."""
    bad_enum = (
        "class MyPlanDispatcher:\n"
        "    def dispatch(self, plan, ctx):\n"
        "        node = self.mapper.replica_nodes(plan.shard)[0]\n"
        "        return node\n"
    )
    got = _replica_routing_violations(bad_enum, "fake.py")
    assert len(got) == 1 and "ReplicaSet.pick" in got[0]
    bad_failover = (
        "def failover_target(shard, nodes):\n"
        "    return sorted(nodes)[0]\n"
    )
    got = _replica_routing_violations(bad_failover, "fake.py")
    assert len(got) == 1 and "failover_target" in got[0]
    ok = (
        "class MyPlanDispatcher:\n"
        "    def dispatch(self, plan, ctx):\n"
        "        for node in self.replica_set.pick(self.shard):\n"
        "            return node\n"
        "def hedge_alternate_for(plan, this_node):\n"
        "    return rs.alternate(plan.shard, exclude=[this_node])\n"
    )
    assert _replica_routing_violations(ok, "fake.py") == []
    # and the policy home itself is exempt
    assert _replica_routing_violations(
        bad_enum, "coordinator/replicas.py") == []
