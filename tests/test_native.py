"""Native C++ codec fast paths vs the pure-numpy reference implementations.

Mirrors the reference's exhaustive codec round-trip strategy (reference:
memory/src/test/scala/filodb.memory/format/EncodingPropertiesTest.scala),
with the numpy implementations acting as the oracle.
"""

import numpy as np
import pytest

from filodb_tpu import native
from filodb_tpu.codecs import deltadelta, doublecodec, nibblepack

pytestmark = pytest.mark.skipif(
    not native.enable(), reason=f"native lib unavailable: {native.build_error()}")


@pytest.fixture(autouse=True)
def _native_on():
    """Each test runs with native enabled; oracle calls disable it locally."""
    native.enable()
    yield
    native.enable()


def _py_pack(values):
    native.disable()
    try:
        return nibblepack.pack(values)
    finally:
        native.enable()


def _py_unpack(buf, count, offset=0):
    native.disable()
    try:
        return nibblepack.unpack(buf, count, offset)
    finally:
        native.enable()


CASES = [
    np.array([], dtype=np.uint64),
    np.zeros(8, dtype=np.uint64),
    np.zeros(17, dtype=np.uint64),
    np.arange(1, 9, dtype=np.uint64),
    np.arange(100, dtype=np.uint64) * 1000,
    np.array([0xFFFFFFFFFFFFFFFF] * 5, dtype=np.uint64),
    np.array([1, 0, 2, 0, 3, 0, 4, 0, 5], dtype=np.uint64),
    np.array([0x10, 0x100, 0x1000, 0x10000], dtype=np.uint64),
]


@pytest.mark.parametrize("vals", CASES, ids=range(len(CASES)))
def test_pack_bitexact_vs_python(vals):
    assert nibblepack.pack(vals) == _py_pack(vals)


@pytest.mark.parametrize("vals", CASES, ids=range(len(CASES)))
def test_unpack_roundtrip(vals):
    buf = nibblepack.pack(vals)
    out, end = nibblepack.unpack(buf, len(vals))
    np.testing.assert_array_equal(out, vals)
    assert end == len(buf)
    # native unpack agrees with python unpack byte-for-byte
    pout, pend = _py_unpack(buf, len(vals))
    np.testing.assert_array_equal(out, pout)
    assert end == pend


def test_fuzz_roundtrip():
    rng = np.random.default_rng(7)
    for _ in range(200):
        n = int(rng.integers(0, 64))
        # mix of magnitudes so nibble widths vary
        shift = rng.integers(0, 60, size=n).astype(np.uint64)
        vals = (rng.integers(0, 2**20, size=n).astype(np.uint64) << shift)
        buf = nibblepack.pack(vals)
        assert buf == _py_pack(vals)
        out, end = nibblepack.unpack(buf, n)
        np.testing.assert_array_equal(out, vals)
        assert nibblepack.packed_end(buf, n) == end


def test_truncated_stream_raises():
    vals = np.arange(1, 30, dtype=np.uint64) * 12345
    buf = nibblepack.pack(vals)
    with pytest.raises(ValueError):
        nibblepack.unpack(buf[:len(buf) // 2], len(vals))


def test_dd_decode_fused():
    rng = np.random.default_rng(3)
    for _ in range(50):
        n = int(rng.integers(0, 300))
        base = int(rng.integers(-2**40, 2**40))
        ts = base + np.cumsum(rng.integers(1, 20000, size=max(n, 1)))[:n]
        ts = ts.astype(np.int64)
        buf = deltadelta.encode(ts)
        native.disable()
        oracle = deltadelta.decode(buf)
        native.enable()
        np.testing.assert_array_equal(deltadelta.decode(buf), oracle)


def test_dd_decode_const():
    ts = (1000 + np.arange(500, dtype=np.int64) * 10_000)
    buf = deltadelta.encode(ts)
    assert buf[0] == 2  # CONST_LONG fast case
    np.testing.assert_array_equal(deltadelta.decode(buf), ts)


def test_dd_corrupt_raises():
    ts = np.cumsum(np.random.default_rng(0).integers(1, 50, 100)).astype(np.int64)
    buf = deltadelta.encode(ts)
    if buf[0] == 2:  # const needs no residual bytes; skip
        pytest.skip("collapsed to const")
    with pytest.raises(ValueError):
        deltadelta.decode(buf[:15])


def test_xor_double_fused():
    rng = np.random.default_rng(11)
    for _ in range(50):
        n = int(rng.integers(1, 400))
        v = rng.normal(size=n) * 10.0 ** float(rng.integers(-3, 6))
        v[rng.random(n) < 0.1] = np.nan  # NaN gap sentinel must survive
        buf = doublecodec.encode(v)
        native.disable()
        oracle = doublecodec.decode(buf)
        native.enable()
        out = doublecodec.decode(buf)
        np.testing.assert_array_equal(
            out.view(np.uint64), oracle.view(np.uint64))  # bit-exact incl. NaN


def test_native_faster_than_python():
    """Sanity: the point of the C++ path is decode throughput."""
    import time

    ts = (10_000 + np.cumsum(
        np.random.default_rng(0).integers(9_000, 11_000, size=10_000))
    ).astype(np.int64)
    buf = deltadelta.encode(ts)
    assert buf[0] != 2  # must exercise the residual path

    native.enable()
    t0 = time.perf_counter()
    for _ in range(20):
        deltadelta.decode(buf)
    t_native = time.perf_counter() - t0

    native.disable()
    t0 = time.perf_counter()
    deltadelta.decode(buf)
    t_py = time.perf_counter() - t0
    native.enable()

    assert t_native / 20 < t_py, (t_native / 20, t_py)
