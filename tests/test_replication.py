"""Replica-group HA unit coverage (ISSUE 7).

Placement: rf-aware assignment with node distinctness, degraded
placement when rf > live nodes (loud), per-replica demotion with
ShardDown + transition metrics, rejoin refresh.  Routing: the single
ReplicaSet.pick helper's status/lag/latency order, ReplicaDispatcher
failover within deadline budget, hedge retargeting a different replica,
both-replicas-down degrading to the honored partial-results path.
Ingest: ReplicaFanout dual-write, a generative convergence sweep
(replicas end bit-identical in index cardinality), recovery promotion
gated on the replica-group head, and promotion racing concurrent
evict/purge."""

import logging
import threading
import time

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import (FailureDetector, ShardDown,
                                            ShardManager)
from filodb_tpu.coordinator.dispatch import (HttpPlanDispatcher,
                                             ReplicaDispatcher,
                                             dispatcher_factory)
from filodb_tpu.coordinator.node import IngestionCoordinator
from filodb_tpu.coordinator.replicas import ReplicaSet
from filodb_tpu.core.record import RecordBuilder
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.ingest.stream import QueueStreamFactory
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.parallel.shardmap import ShardMapper, ShardStatus
from filodb_tpu.query.exec import (DistConcatExec, ExecContext,
                                   MultiSchemaPartitionsExec, PlanDispatcher)
from filodb_tpu.query.model import (QueryContext, QueryResult, QueryStats,
                                    ShardUnavailable)
from filodb_tpu.utils.observability import REGISTRY

BASE = 1_700_000_000_000


# ---------------------------------------------------------------------------
# Placement
# ---------------------------------------------------------------------------


class TestReplicatedPlacement:
    def test_rf2_places_each_shard_on_two_distinct_nodes(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 4, min_num_nodes=2, replication_factor=2)
        mgr.add_node("a")
        mgr.add_node("b")
        m = mgr.mapper("ds")
        for s in range(4):
            nodes = m.replica_nodes(s)
            assert len(nodes) == 2
            assert len(set(nodes)) == 2, "same node twice in one group"
        # even spread: 4 shards x 2 copies over 2 nodes = 4 each
        assert len(m.shards_for_node("a")) == 4
        assert len(m.shards_for_node("b")) == 4

    def test_rf2_three_nodes_spreads_copies(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 4, min_num_nodes=3, replication_factor=2)
        for n in ("a", "b", "c"):
            mgr.add_node(n)
        m = mgr.mapper("ds")
        loads = sorted(len(m.shards_for_node(n)) for n in ("a", "b", "c"))
        assert sum(loads) == 8                 # 4 shards x 2 replicas
        assert loads[-1] <= 3                  # ceil(8/3)
        for s in range(4):
            assert len(set(m.replica_nodes(s))) == 2

    def test_assignment_idempotent_at_rf2(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 4, min_num_nodes=2, replication_factor=2)
        first = mgr.add_node("a")["ds"]
        again = mgr.add_node("a")["ds"]
        assert first == again

    def test_rf_above_live_nodes_degrades_loudly(self, caplog):
        from filodb_tpu.utils.devicewatch import FLIGHT
        mgr = ShardManager()
        with caplog.at_level(logging.WARNING,
                             logger="filodb_tpu.coordinator.cluster"):
            mgr.setup_dataset("lonely", 2, min_num_nodes=1,
                              replication_factor=2)
            mgr.add_node("only-node")
        m = mgr.mapper("lonely")
        for s in range(2):
            assert m.replica_nodes(s) == ["only-node"]  # degraded, serving
        assert any("degraded placement" in r.message for r in caplog.records)
        evs = [e for e in FLIGHT.events(kind="shard.degraded_placement")
               if e.get("dataset") == "lonely"]
        assert evs and evs[-1]["replication_factor"] == 2

    def test_remove_node_demotes_replica_publishes_sharddown(self):
        events = []
        trans = REGISTRY.counter("filodb_shard_status_transitions_total")
        mgr = ShardManager()
        mgr.subscribe(events.append)
        mgr.setup_dataset("rep1", 2, min_num_nodes=2, replication_factor=2)
        mgr.add_node("a")
        mgr.add_node("b")
        before = trans.value(dataset="rep1", status="Down")
        m = mgr.mapper("rep1")
        for s in range(2):
            for r in m.replicas(s):
                m.update_status(s, ShardStatus.ACTIVE, node=r.node)
        mgr.remove_node("a")
        downs = [e for e in events if isinstance(e, ShardDown)]
        assert {e.shard for e in downs} == {0, 1}
        assert all(e.node == "a" for e in downs)
        # named-mapper path: one Down transition per lost REPLICA
        assert trans.value(dataset="rep1", status="Down") == before + 2
        # the surviving replica keeps each shard queryable
        for s in range(2):
            assert m.best_status(s) is ShardStatus.ACTIVE
            live = m.live_replicas(s)
            assert [r.node for r in live] == ["b"]

    def test_failure_detector_check_drives_replica_demotion(self):
        clock = [100.0]
        events = []
        mgr = ShardManager(clock=lambda: clock[0])
        mgr.subscribe(events.append)
        mgr.setup_dataset("rep2", 2, min_num_nodes=2, replication_factor=2)
        fd = FailureDetector(mgr, timeout_ms=5_000, clock=lambda: clock[0])
        fd.heartbeat("a")
        fd.heartbeat("b")
        clock[0] += 3.0
        fd.heartbeat("b")
        clock[0] += 3.0
        assert fd.check() == ["a"]
        downs = [e for e in events if isinstance(e, ShardDown)]
        assert downs and all(e.node == "a" for e in downs)
        m = mgr.mapper("rep2")
        for s in range(2):
            assert all(r.node == "b" for r in m.live_replicas(s))

    def test_rejoin_refreshes_down_replica(self):
        mgr = ShardManager()
        mgr.setup_dataset("ds", 2, min_num_nodes=2, replication_factor=2)
        mgr.add_node("a")
        mgr.add_node("b")
        mgr.remove_node("a")   # no third node: groups degraded, a's
        m = mgr.mapper("ds")   # replicas stay marked Down
        for s in range(2):
            assert len(m.live_replicas(s)) == 1
        mgr.add_node("a")      # rejoin: same node picks its shards back
        for s in range(2):
            assert len(m.live_replicas(s)) == 2
            rep = m.state(s).replica("a")
            assert rep is not None
            assert rep.status is ShardStatus.ASSIGNED

    def test_losing_last_node_fires_degraded_warning(self, caplog):
        """Regression (review): removing the FINAL node — the worst
        placement transition of all — must still fire the loud
        degraded warning; the reassignment early-return (no survivors
        to move shards to) used to skip it."""
        from filodb_tpu.utils.devicewatch import FLIGHT
        mgr = ShardManager()
        mgr.setup_dataset("lastn", 2, min_num_nodes=1,
                          replication_factor=1)
        mgr.add_node("a")           # rf=1 met: placement healthy
        ev = lambda: len(
            [e for e in FLIGHT.events(kind="shard.degraded_placement")
             if e.get("dataset") == "lastn"])
        before = ev()
        with caplog.at_level(logging.WARNING,
                             logger="filodb_tpu.coordinator.cluster"):
            mgr.remove_node("a")
        assert ev() == before + 1
        assert any("degraded placement" in r.message
                   for r in caplog.records)

    def test_set_replicas_adopts_membership_keeps_local_status(self):
        m = ShardMapper(2, replication_factor=2)
        m.register_node([0], "a")
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        changed = m.set_replicas(0, [
            {"node": "a", "status": "Assigned"},
            {"node": "c", "status": "Recovery", "watermark": 7}])
        assert changed
        assert m.replica_nodes(0) == ["a", "c"]
        # retained replica keeps LOCAL status; new one takes the leader's
        assert m.state(0).replica("a").status is ShardStatus.ACTIVE
        assert m.state(0).replica("c").status is ShardStatus.RECOVERY
        assert m.state(0).replica("c").watermark == 7
        assert not m.set_replicas(0, [{"node": "a"}, {"node": "c"}])

    def test_set_replicas_primary_demotion_fires_shard_transition(self):
        """Regression (review): a follower adopting a leader view that
        demotes the PRIMARY replica across the down boundary must emit
        the shard.status flight event — prev has to be read BEFORE the
        kept replicas are mutated in place, or the comparison sees the
        new status on both sides and the transition never fires.  The
        shard-level gauge meanwhile reports the SERVING view: the
        surviving Active peer keeps the shard green (a dead primary of
        a fully-served shard must not page)."""
        from filodb_tpu.utils.devicewatch import FLIGHT
        gauge = REGISTRY.gauge("filodb_shard_status_code")
        m = ShardMapper(1, dataset="adopt1", replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        m.update_status(0, ShardStatus.ACTIVE, node="b")
        assert gauge.value(dataset="adopt1", shard=0) == 3  # Active
        m.set_replicas(0, [{"node": "a", "status": "Down"},
                           {"node": "b", "status": "Active"}])
        assert m.status(0) is ShardStatus.DOWN      # primary view
        assert m.best_status(0) is ShardStatus.ACTIVE
        assert gauge.value(dataset="adopt1", shard=0) == 3  # serving
        evs = [e for e in FLIGHT.events(kind="shard.status")
               if e.get("dataset") == "adopt1"]
        assert evs and evs[-1]["status"] == "Down" \
            and evs[-1]["prev"] == "Active"
        # both copies gone -> the gauge DOES go Down
        m.set_replicas(0, [{"node": "a", "status": "Down"},
                           {"node": "b", "status": "Down"}])
        assert gauge.value(dataset="adopt1", shard=0) == 6  # Down

    def test_displaced_replica_gauge_row_removed(self):
        """Regression (review): replacing a replica (rf=1 move, rf>1
        dead-copy replacement) must remove the displaced copy's
        filodb_shard_replica_status_code row, not export it forever."""
        gauge = REGISTRY.gauge("filodb_shard_replica_status_code")
        m = ShardMapper(2, dataset="disp1")
        m.register_node([0], "a")
        m.register_node([0], "b")           # rf=1 move: a displaced
        assert gauge.value(dataset="disp1", shard=0, node="b") == 1
        assert ("disp1", 0, "a") not in {
            (dict(k).get("dataset"), dict(k).get("shard"),
             dict(k).get("node")) for k in gauge._values}
        m2 = ShardMapper(2, dataset="disp2", replication_factor=2)
        m2.register_node([0], "a")
        m2.register_node([0], "b")
        m2.update_status(0, ShardStatus.DOWN, node="a")
        m2.register_node([0], "c")          # replaces the dead copy
        assert m2.replica_nodes(0) == ["c", "b"]
        assert ("disp2", 0, "a") not in {
            (dict(k).get("dataset"), dict(k).get("shard"),
             dict(k).get("node")) for k in gauge._values}

    def test_second_replica_addition_counts_a_transition(self):
        """Regression (review): adding a copy to a non-empty group must
        count its Unassigned->Assigned transition (the counter owns
        per-REPLICA transitions)."""
        trans = REGISTRY.counter("filodb_shard_status_transitions_total")
        m = ShardMapper(2, dataset="add2", replication_factor=2)
        m.register_node([0], "a")
        before = trans.value(dataset="add2", status="Assigned")
        m.register_node([0], "b")
        assert trans.value(dataset="add2", status="Assigned") == before + 1

    def test_leader_demotion_propagates_to_followers(self):
        """Regression (review): a follower adopting the leader's view
        must take leader-intent statuses that CROSS the down boundary —
        a demotion to Down (else the follower routes at a dead replica
        forever) and the later resurrection — while keeping its own
        liveness view within live states."""
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        m.update_status(0, ShardStatus.ACTIVE, node="b")
        # leader demoted b: follower adopts Down
        m.set_replicas(0, [{"node": "a", "status": "Active"},
                           {"node": "b", "status": "Down"}])
        assert m.state(0).replica("b").status is ShardStatus.DOWN
        # within live states the local view stays authoritative
        m.set_replicas(0, [{"node": "a", "status": "Recovery"},
                           {"node": "b", "status": "Down"}])
        assert m.state(0).replica("a").status is ShardStatus.ACTIVE
        # leader resurrected b after rejoin: follower adopts that too
        m.set_replicas(0, [{"node": "a", "status": "Active"},
                           {"node": "b", "status": "Assigned"}])
        assert m.state(0).replica("b").status is ShardStatus.ASSIGNED

    def test_error_replica_not_double_assigned(self):
        """Regression (review): an Error copy must not land a shard in
        BOTH the strategy's have and need sides (duplicate assignment +
        duplicate ShardAssignmentStarted events)."""
        from filodb_tpu.coordinator.cluster import (
            DefaultShardAssignmentStrategy, ShardAssignmentStarted)
        m = ShardMapper(2, replication_factor=2)
        m.register_node([0, 1], "n1")
        m.register_node([0, 1], "n2")
        m.update_status(0, ShardStatus.ERROR, node="n1")
        strat = DefaultShardAssignmentStrategy()
        got = strat.shard_assignments("n1", "ds", m, 2)
        assert len(got) == len(set(got)), got
        # and a full manager pass publishes ONE event per assignment
        mgr = ShardManager()
        mgr.setup_dataset("err1", 2, min_num_nodes=2,
                          replication_factor=2)
        events = []
        mgr.subscribe(events.append)
        mgr.add_node("n1")
        starts = [e for e in events
                  if isinstance(e, ShardAssignmentStarted)]
        assert len(starts) == len({(e.shard, e.node) for e in starts})

    def test_liveness_fallback_preserves_recovery_progress(self):
        """Regression (review): a peer health body without 'running'
        must not wipe its recovering replica's progress to 0 every
        sweep."""
        from filodb_tpu.coordinator.cluster import (FailureDetector,
                                                    ShardManager,
                                                    StatusPoller)
        mgr = ShardManager()
        det = FailureDetector(mgr, timeout_ms=1000)
        poller = StatusPoller(mgr, det, {"node-b": "http://x"}, "node-a")
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        target = m.shards_for_node("node-b")[0]
        m.update_status(target, ShardStatus.RECOVERY, progress=40,
                        node="node-b")
        poller._apply_liveness("node-b", {"shards": {"ds": [
            {"shard": target, "status": "Recovery",
             "replicas": [{"node": "node-b", "status": "Recovery",
                           "progress": 40}]}]}})
        assert m.state(target).replica("node-b").recovery_progress == 40
        poller.stop()

    def test_liveness_live_branch_carries_gossiped_progress(self):
        """Regression (review, round 2): the NORMAL path — peer reports
        'running' — must adopt the peer's own gossiped recovery
        progress, not the locally-stored value.  The owner's recovery
        events never reach this node's ShardManager and register_node
        reset the local copy to 0 at rejoin, so without the adoption
        every non-owner surface showed a recovering replica stuck at 0%
        for the whole replay."""
        from filodb_tpu.coordinator.cluster import (FailureDetector,
                                                    ShardManager,
                                                    StatusPoller)
        mgr = ShardManager()
        det = FailureDetector(mgr, timeout_ms=1000)
        poller = StatusPoller(mgr, det, {"node-b": "http://x"}, "node-a")
        mgr.setup_dataset("ds", 2, min_num_nodes=2)
        det.heartbeat("node-b")
        m = mgr.mapper("ds")
        target = m.shards_for_node("node-b")[0]
        # local view: rejoin reset the replica's progress to 0
        m.update_status(target, ShardStatus.RECOVERY, progress=0,
                        node="node-b")
        poller._apply_liveness("node-b", {
            "running": {"ds": [target]},
            "shards": {"ds": [
                {"shard": target, "status": "Recovery",
                 "replicas": [{"node": "node-b", "status": "Recovery",
                               "progress": 65}]}]}})
        rep = m.state(target).replica("node-b")
        assert rep.status is ShardStatus.RECOVERY
        assert rep.recovery_progress == 65
        poller.stop()

    def test_watermarks_and_group_head(self):
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        assert m.group_head(0) == -1
        m.note_watermark(0, "a", 100)
        m.note_watermark(0, "b", 40)
        assert m.group_head(0) == 100
        m.note_watermark(0, "b", 30)   # watermarks never regress...
        assert m.state(0).replica("b").watermark == 40
        # ...EXCEPT across a rejoin (review regression): the node
        # restarts and replays from its checkpoint — the pre-crash
        # watermark is stale and must reset, or lag views hide the
        # replay regression forever
        m.update_status(0, ShardStatus.DOWN, node="b")
        m.register_node([0], "b")
        assert m.state(0).replica("b").watermark == -1
        # same rule on followers adopting a leader's resurrection
        m2 = ShardMapper(1, replication_factor=2)
        m2.register_node([0], "a")
        m2.register_node([0], "b")
        m2.note_watermark(0, "b", 10_000)
        m2.update_status(0, ShardStatus.DOWN, node="b")
        m2.set_replicas(0, [{"node": "a", "status": "Active"},
                            {"node": "b", "status": "Assigned",
                             "watermark": -1}])
        assert m2.state(0).replica("b").watermark == -1


# ---------------------------------------------------------------------------
# Routing: ReplicaSet.pick
# ---------------------------------------------------------------------------


class TestReplicaSetPick:
    def _mapper(self):
        m = ShardMapper(1, replication_factor=3)
        for n in ("a", "b", "c"):
            m.register_node([0], n)
        return m

    def test_active_before_recovery_recovery_only_without_active(self):
        m = self._mapper()
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        m.update_status(0, ShardStatus.RECOVERY, node="b")
        m.update_status(0, ShardStatus.ACTIVE, node="c")
        rs = ReplicaSet(m)
        # a recovering copy is NEVER picked while an Active peer exists
        assert set(rs.pick(0)) == {"a", "c"}
        m.update_status(0, ShardStatus.DOWN, node="a")
        m.update_status(0, ShardStatus.DOWN, node="c")
        assert rs.pick(0) == ["b"]     # no Active: Recovery serves

    def test_down_replicas_never_picked(self):
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.DOWN, node=n)
        assert ReplicaSet(m).pick(0) == []

    def test_watermark_lag_orders_active_replicas(self):
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        m.note_watermark(0, "a", 10_000)
        m.note_watermark(0, "b", 5_000)    # far behind the head
        m.note_watermark(0, "c", 10_000)
        order = ReplicaSet(m, lag_tolerance_rows=256).pick(0)
        assert order.index("b") == 2       # the laggard ranks last
        assert set(order[:2]) == {"a", "c"}

    def test_unknown_watermark_ranks_worst_when_peers_are_known(self):
        """Regression (review): a replica whose watermark has not been
        gossiped yet (-1) must not tie with the group head and win on
        latency — it may be arbitrarily diverged."""
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        m.note_watermark(0, "a", 10_000)
        m.note_watermark(0, "c", 9_999)
        # b unknown, and even LOCAL (latency 0): still ranks last
        order = ReplicaSet(m, local_node="b").pick(0)
        assert order[-1] == "b", order

    def test_small_lag_jitter_does_not_flap(self):
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        m.note_watermark(0, "a", 10_000)
        m.note_watermark(0, "b", 9_990)    # in-flight rows, not a lag
        m.note_watermark(0, "c", 10_000)
        order = ReplicaSet(m, lag_tolerance_rows=256).pick(0)
        assert order == ["a", "b", "c"]    # stable name order, no demotion

    def test_local_node_preferred_then_calibrated_latency(self):
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        lat = {"a": 0.5, "b": 0.001, "c": None}
        rs = ReplicaSet(m, local_node="c", latency_fn=lat.get)
        assert rs.pick(0)[0] == "c"        # local first (no hop)
        rs2 = ReplicaSet(m, latency_fn=lat.get)
        assert rs2.pick(0) == ["b", "a", "c"]  # calibrated before unknown

    def test_recovery_never_serves_while_group_has_active(self):
        """Regression (review): the Recovery gate is over the WHOLE
        group — excluding the (slow/just-failed) Active replica must
        NOT let a mid-replay Recovery copy answer with stale windows;
        the caller fails loudly instead."""
        m = self._mapper()
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        m.update_status(0, ShardStatus.RECOVERY, node="b")
        m.update_status(0, ShardStatus.DOWN, node="c")
        rs = ReplicaSet(m)
        assert rs.pick(0, exclude=["a"]) == []
        assert rs.alternate(0, exclude=["a"]) is None
        # once the Active copy is DEMOTED (no Active anywhere), the
        # Recovery copy may serve
        m.update_status(0, ShardStatus.DOWN, node="a")
        assert rs.pick(0) == ["b"]

    def test_exclude_and_alternate(self):
        m = self._mapper()
        for n in ("a", "b", "c"):
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        rs = ReplicaSet(m)
        assert rs.pick(0, exclude=["a"]) == ["b", "c"]
        assert rs.alternate(0, exclude=["a", "b"]) == "c"
        assert rs.alternate(0, exclude=["a", "b", "c"]) is None

    def test_startup_fallback_serves_assigned(self):
        m = self._mapper()                 # all replicas still Assigned
        assert ReplicaSet(m).pick(0) == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# Routing: failover dispatch
# ---------------------------------------------------------------------------


class _FakeDispatcher(PlanDispatcher):
    def __init__(self, name, fail=False, log=None):
        self.name = name
        self.fail = fail
        self.log = log if log is not None else []

    def dispatch(self, plan, ctx):
        self.log.append(self.name)
        if self.fail:
            raise ShardUnavailable("q", f"remote dispatch to {self.name} "
                                        f"failed after 1 attempt(s)")
        return QueryResult("q", [], QueryStats())


def _rf2_mapper(statuses=("Active", "Active")):
    m = ShardMapper(1, replication_factor=2)
    m.register_node([0], "a")
    m.register_node([0], "b")
    for node, st in zip(("a", "b"), statuses):
        m.update_status(0, ShardStatus(st), node=node)
    return m


class TestFailoverDispatch:
    def _plan(self, qctx=None):
        return MultiSchemaPartitionsExec("prom", 0, [], BASE, BASE + 1000,
                                         query_context=qctx)

    def test_failover_to_next_replica_on_shard_unavailable(self):
        from filodb_tpu.utils.devicewatch import FLIGHT
        failover = REGISTRY.counter("filodb_dispatch_failover_total")
        before = failover.value(reason="unreachable")
        m = _rf2_mapper()
        log = []
        fakes = {"a": _FakeDispatcher("a", fail=True, log=log),
                 "b": _FakeDispatcher("b", fail=False, log=log)}
        rd = ReplicaDispatcher("prom", 0, ReplicaSet(m),
                               lambda s, n: fakes[n])
        out = rd.dispatch(self._plan(), ExecContext(TimeSeriesMemStore(),
                                                    QueryContext()))
        assert isinstance(out, QueryResult)
        assert log == ["a", "b"]
        assert failover.value(reason="unreachable") == before + 1
        evs = [e for e in FLIGHT.events(kind="dispatch.failover")
               if e.get("dataset") == "prom"]
        assert evs and evs[-1]["from_node"] == "a" \
            and evs[-1]["to_node"] == "b"

    def test_failover_reason_comes_from_the_raise_site_tag(self):
        """Regression (review): urllib's '[Errno 111] Connection
        refused' in an exhausted-retries message must classify as
        'unreachable'; only a tagged 503 work-refusal counts as
        'refused'."""
        failover = REGISTRY.counter("filodb_dispatch_failover_total")
        before_un = failover.value(reason="unreachable")
        before_ref = failover.value(reason="refused")
        m = _rf2_mapper()

        class _TaggedFail(PlanDispatcher):
            def __init__(self, reason=None):
                self.reason = reason

            def dispatch(self, plan, ctx):
                e = ShardUnavailable(
                    "q", "remote dispatch to x failed after 2 "
                         "attempt(s): <urlopen error [Errno 111] "
                         "Connection refused>")
                if self.reason:
                    e.reason = self.reason
                raise e

        ok = _FakeDispatcher("b")
        rd = ReplicaDispatcher(
            "prom", 0, ReplicaSet(m),
            lambda s, n: _TaggedFail() if n == "a" else ok)
        rd.dispatch(self._plan(), ExecContext(TimeSeriesMemStore(),
                                              QueryContext()))
        assert failover.value(reason="unreachable") == before_un + 1
        assert failover.value(reason="refused") == before_ref
        rd2 = ReplicaDispatcher(
            "prom", 0, ReplicaSet(m),
            lambda s, n: _TaggedFail("refused") if n == "a" else ok)
        rd2.dispatch(self._plan(), ExecContext(TimeSeriesMemStore(),
                                               QueryContext()))
        assert failover.value(reason="refused") == before_ref + 1

    def test_whole_group_down_raises_shard_unavailable(self):
        m = _rf2_mapper()
        log = []
        fakes = {"a": _FakeDispatcher("a", fail=True, log=log),
                 "b": _FakeDispatcher("b", fail=True, log=log)}
        rd = ReplicaDispatcher("prom", 0, ReplicaSet(m),
                               lambda s, n: fakes[n])
        with pytest.raises(ShardUnavailable):
            rd.dispatch(self._plan(), ExecContext(TimeSeriesMemStore(),
                                                  QueryContext()))
        assert log == ["a", "b"]           # every replica was tried

    def test_failover_respects_exhausted_deadline(self):
        m = _rf2_mapper()
        log = []
        fakes = {"a": _FakeDispatcher("a", fail=True, log=log),
                 "b": _FakeDispatcher("b", fail=False, log=log)}
        qctx = QueryContext(submit_time_ms=int(time.time() * 1000))
        qctx.deadline_ms = int(time.time() * 1000) - 1   # already gone
        rd = ReplicaDispatcher("prom", 0, ReplicaSet(m),
                               lambda s, n: fakes[n])
        with pytest.raises(ShardUnavailable):
            rd.dispatch(self._plan(qctx),
                        ExecContext(TimeSeriesMemStore(), qctx))
        assert log == ["a"]                # no budget left to fail over

    def test_both_replicas_down_partial_results_path_honored(self):
        """The acceptance edge: with the WHOLE group dead, the query
        still degrades to the PR 10 partial-results contract when (and
        only when) the client opted in."""
        m = _rf2_mapper()
        f = dispatcher_factory(
            m, {"a": "http://127.0.0.1:1", "b": "http://127.0.0.1:1"},
            local_node="coordinator",
            dispatch_config={"retries": 0, "backoff-s": 0.0})
        rd = f(0)
        assert isinstance(rd, ReplicaDispatcher)
        qctx = QueryContext(allow_partial_results=True)
        leaf = MultiSchemaPartitionsExec("prom", 0, [], BASE, BASE + 1000,
                                         query_context=qctx, dispatcher=rd)
        root = DistConcatExec([leaf], qctx)
        res = root.execute(ExecContext(TimeSeriesMemStore(), qctx))
        assert res.batches == []
        assert res.stats.shards_down == 1
        # without the opt-in: loud failure
        qctx2 = QueryContext(allow_partial_results=False)
        leaf2 = MultiSchemaPartitionsExec("prom", 0, [], BASE, BASE + 1000,
                                          query_context=qctx2, dispatcher=rd)
        with pytest.raises(ShardUnavailable):
            DistConcatExec([leaf2], qctx2).execute(
                ExecContext(TimeSeriesMemStore(), qctx2))

    def test_missing_endpoint_failover_is_counted(self):
        """Regression (review): skipping a replica because its node has
        no endpoint is a failover too — counted + flight-recorded, not
        silent."""
        failover = REGISTRY.counter("filodb_dispatch_failover_total")
        before = failover.value(reason="no_endpoint")
        m = _rf2_mapper()
        log = []
        fakes = {"a": None,
                 "b": _FakeDispatcher("b", fail=False, log=log)}
        rd = ReplicaDispatcher("prom", 0, ReplicaSet(m),
                               lambda s, n: fakes[n])
        out = rd.dispatch(self._plan(), ExecContext(TimeSeriesMemStore(),
                                                    QueryContext()))
        assert isinstance(out, QueryResult) and log == ["b"]
        assert failover.value(reason="no_endpoint") == before + 1

    def test_failover_excludes_burned_replicas_from_hedge(self):
        """Regression (review): after a failover, the hedge retarget
        hook must not aim the duplicate at the replica that JUST
        failed (plan.replica_exclude threads the tried set)."""
        m = ShardMapper(1, replication_factor=3)
        for n in ("a", "b", "c"):
            m.register_node([0], n)
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        f = dispatcher_factory(
            m, {"a": "http://127.0.0.1:41011",
                "b": "http://127.0.0.1:41012",
                "c": "http://127.0.0.1:41013"},
            local_node="coordinator",
            dispatch_config={"retries": 0, "hedge": True})
        rd = f(0)
        d_b = rd.dispatcher_for_node(0, "b")
        plan = self._plan()
        plan.replica_exclude = ["a"]   # the failover loop burned a
        alt = d_b.hedge_alternate(plan)
        assert alt == "http://127.0.0.1:41013", alt

    def test_hedge_skips_alias_of_inflight_endpoint(self):
        """Regression (review): two node names resolving to ONE
        endpoint (misconfiguration) must not emit hedge_retarget
        telemetry for a duplicate ``_send_hedged`` would discard as
        same-endpoint — the walk continues to a genuinely different
        replica and telemetry fires only for the real retarget."""
        failover = REGISTRY.counter("filodb_dispatch_failover_total")
        before = failover.value(reason="hedge_retarget")
        m = ShardMapper(1, replication_factor=3)
        for n in ("a", "b", "c"):
            m.register_node([0], n)
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        # b is an alias of a's endpoint; ranking visits b before c
        f = dispatcher_factory(
            m, {"a": "http://127.0.0.1:41031",
                "b": "http://127.0.0.1:41031/",
                "c": "http://127.0.0.1:41033"},
            local_node="coordinator",
            dispatch_config={"retries": 0, "hedge": True})
        rd = f(0)
        d_a = rd.dispatcher_for_node(0, "a")
        alt = d_a.hedge_alternate(self._plan())
        assert alt == "http://127.0.0.1:41033", alt
        assert failover.value(reason="hedge_retarget") == before + 1

    def test_hedge_walks_past_endpointless_replica(self):
        """Regression (review): when the best alternate has no
        configured endpoint, the hedge walks to the NEXT replica
        (like the failover loop's no_endpoint continue) instead of
        degrading to a same-endpoint duplicate at the wedged node."""
        m = ShardMapper(1, replication_factor=3)
        for n in ("a", "b", "c"):
            m.register_node([0], n)
            m.update_status(0, ShardStatus.ACTIVE, node=n)
        # all-Active + no latency data ranks by node name: b before c;
        # b has NO endpoint, so the hedge must walk on to c
        f = dispatcher_factory(
            m, {"a": "http://127.0.0.1:41021",
                "c": "http://127.0.0.1:41023"},
            local_node="coordinator",
            dispatch_config={"retries": 0, "hedge": True})
        rd = f(0)
        d_a = rd.dispatcher_for_node(0, "a")
        alt = d_a.hedge_alternate(self._plan())
        assert alt == "http://127.0.0.1:41023", alt

    def test_factory_returns_legacy_shapes_at_rf1(self):
        from filodb_tpu.query.exec import IN_PROCESS
        m = ShardMapper(2)
        m.register_node([0], "a")
        m.register_node([1], "b")
        f = dispatcher_factory(m, {"b": "http://x:1"}, local_node="a")
        assert f(0) is IN_PROCESS
        assert isinstance(f(1), HttpPlanDispatcher)

    def test_hedged_duplicate_retargets_other_replica(self, monkeypatch):
        """The hedge's second request goes to a DIFFERENT replica,
        selected through ReplicaSet.pick (via the alternate hook)."""
        m = _rf2_mapper()
        f = dispatcher_factory(
            m, {"a": "http://127.0.0.1:41001", "b": "http://127.0.0.1:41002"},
            local_node="coordinator",
            dispatch_config={"retries": 0, "hedge": True,
                             "hedge-min-s": 0.01})
        rd = f(0)
        assert isinstance(rd, ReplicaDispatcher)
        primary = rd.dispatcher_for_node(0, "a")
        for _ in range(32):                # arm the p99 trigger
            primary._note_latency(0.001)
        sent = []
        payload = {"query_id": "q", "batches": [], "stats": {}}

        def fake_send(body, headers, timeout_s, endpoint=None):
            sent.append(endpoint or primary.endpoint)
            if endpoint is None:
                time.sleep(0.5)            # primary wedged: hedge fires
            return payload

        monkeypatch.setattr(primary, "_send_once", fake_send)
        out = primary.dispatch(self._plan(),
                               ExecContext(TimeSeriesMemStore(),
                                           QueryContext()))
        assert isinstance(out, QueryResult)
        assert "http://127.0.0.1:41002" in sent, \
            f"hedge never retargeted the peer replica: {sent}"


# ---------------------------------------------------------------------------
# Ingest: dual-write fanout + convergence
# ---------------------------------------------------------------------------


def _mk_stores(mapper, nodes, dataset="prom"):
    stores = {}
    offsets = {}
    per_node = {}
    for node in nodes:
        ms = TimeSeriesMemStore()
        for s in range(mapper.num_shards):
            ms.setup(dataset, DEFAULT_SCHEMAS, s)
        stores[node] = ms

        def push(shard, container, _ms=ms, _node=node):
            key = (_node, shard)
            off = offsets.get(key, -1) + 1
            offsets[key] = off
            _ms.get_shard(dataset, shard).ingest_container(container, off)

        per_node[node] = push
    return stores, per_node


class TestHealthServingView:
    def test_one_dead_replica_keeps_health_green(self):
        """Regression (review): /__health reports the SERVING view at
        the shard level — one dead copy of a fully-served rf=2 shard
        must not flip healthy:false (503) on every surviving node and
        let a load balancer drain a cluster that serves all data."""
        import json as _json
        import urllib.request

        from filodb_tpu.http.server import FiloHttpServer
        mgr = ShardManager()
        mgr.setup_dataset("hlth", 2, min_num_nodes=2,
                          replication_factor=2)
        mgr.add_node("a")
        mgr.add_node("b")
        m = mgr.mapper("hlth")
        for s in range(2):
            for r in m.replicas(s):
                m.update_status(s, ShardStatus.ACTIVE, node=r.node)
        mgr.remove_node("a")           # demotes a's replicas to Down
        srv = FiloHttpServer(shard_manager=mgr)
        port = srv.start()
        try:
            body = _json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/__health", timeout=10).read())
        finally:
            srv.shutdown()
        assert body["healthy"] is True
        assert {s["status"] for s in body["shards"]["hlth"]} == {"Active"}
        # per-replica truth still rides in the replicas rows (gossip)
        rep_statuses = {r["status"] for s in body["shards"]["hlth"]
                        for r in s["replicas"]}
        assert "Down" in rep_statuses


class TestReplicaFanout:
    def test_dual_write_reaches_every_replica(self):
        from filodb_tpu.gateway.server import ReplicaFanout, ShardingPublisher
        m = ShardMapper(2, replication_factor=2)
        m.register_node([0, 1], "a")
        m.register_node([0, 1], "b")
        stores, per_node = _mk_stores(m, ("a", "b"))
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], m,
                                ReplicaFanout("prom", m, per_node,
                                              local_node="a"),
                                spread=1)
        for i in range(50):
            pub.add_sample("up", {"instance": f"i{i}", "_ws_": "w",
                                  "_ns_": "n"}, BASE + i * 1000, float(i))
        pub.flush()
        assert pub.publish.drain(timeout_s=10), "peer lane never drained"
        rows = {n: sum(sh.stats.rows_ingested
                       for sh in stores[n].shards("prom"))
                for n in ("a", "b")}
        assert rows["a"] == rows["b"] == 50

    def test_one_failing_replica_does_not_block_the_other(self):
        from filodb_tpu.gateway.server import ReplicaFanout
        fails = REGISTRY.counter(
            "filodb_ingest_replica_publish_failures_total")
        before = fails.value(dataset="prom", node="b")
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        got = []

        def boom(shard, container):
            raise OSError("replica b unreachable")

        fan = ReplicaFanout("prom", m,
                            {"a": lambda s, c: got.append(c), "b": boom},
                            local_node="a")
        # local delivered synchronously; the peer's failure happens on
        # its own lane and is counted there
        assert fan(0, b"container") == 2   # local sync + lane-accepted
        assert got == [b"container"]
        fan.drain(timeout_s=10)
        assert fails.value(dataset="prom", node="b") == before + 1

    def test_down_replica_not_dual_written(self):
        """Regression (review): a terminal Down copy stops receiving
        containers (no pinned lane / per-container failure churn for a
        permanently dead peer); delivery resumes when it rejoins."""
        from filodb_tpu.gateway.server import ReplicaFanout
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        got = {"a": [], "b": []}
        fan = ReplicaFanout("downskip", m,
                            {"a": lambda s, c: got["a"].append(c),
                             "b": lambda s, c: got["b"].append(c)},
                            local_node="a")
        m.update_status(0, ShardStatus.DOWN, node="b")
        assert fan(0, b"c1") == 1
        m.update_status(0, ShardStatus.ASSIGNED, node="b")  # rejoined
        assert fan(0, b"c2") == 2
        assert fan.drain(timeout_s=10)
        assert got["a"] == [b"c1", b"c2"]
        assert got["b"] == [b"c2"]

    def test_stopped_replica_not_dual_written(self):
        """Regression (review): an operator-STOPPED replica's ingestion
        consumer is not running (runnable_shards_for_node), so dual-
        writing to it would buffer containers into an unbounded queue
        nothing drains; delivery resumes when the shard restarts."""
        from filodb_tpu.gateway.server import ReplicaFanout
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        got = {"a": [], "b": []}
        fan = ReplicaFanout("stopskip", m,
                            {"a": lambda s, c: got["a"].append(c),
                             "b": lambda s, c: got["b"].append(c)},
                            local_node="a")
        m.update_status(0, ShardStatus.STOPPED, node="b")
        assert fan(0, b"c1") == 1
        m.update_status(0, ShardStatus.ACTIVE, node="b")   # restarted
        assert fan(0, b"c2") == 2
        assert fan.drain(timeout_s=10)
        assert got["a"] == [b"c1", b"c2"]
        assert got["b"] == [b"c2"]

    def test_all_terminal_group_is_not_rerouted_to_local(self):
        """Regression (review, 2 rounds): when EVERY assigned replica is
        terminal the containers are dropped LOUDLY — one failure-counter
        inc per container under node="(all-terminal)" and one flight
        event per episode — not silently buffered into the local node's
        consumerless queue (the copies rejoin from their own
        checkpoints, never from a bystander's queue).  The local
        fallback fires only while the shard is assigned NOWHERE
        (startup), and the episode re-arms once a copy comes back."""
        from filodb_tpu.gateway.server import ReplicaFanout
        from filodb_tpu.utils.devicewatch import FLIGHT
        fails = REGISTRY.counter(
            "filodb_ingest_replica_publish_failures_total")
        before = fails.value(dataset="allterm", node="(all-terminal)")
        ev_count = lambda: len(
            [e for e in FLIGHT.events(kind="ingest.replica_publish_failed")
             if e.get("dataset") == "allterm"
             and e.get("node") == "(all-terminal)"])
        ev_before = ev_count()
        m = ShardMapper(1, replication_factor=2)
        got = {"a": [], "b": [], "c": []}
        fan = ReplicaFanout("allterm", m,
                            {n: (lambda s, c, _n=n: got[_n].append(c))
                             for n in ("a", "b", "c")},
                            local_node="c")
        # unassigned anywhere: the startup fallback keeps data flowing
        assert fan(0, b"boot") == 1
        assert got["c"] == [b"boot"]
        m.register_node([0], "a")
        m.register_node([0], "b")
        m.update_status(0, ShardStatus.DOWN, node="a")
        m.update_status(0, ShardStatus.DOWN, node="b")
        assert fan(0, b"outage") == 0      # dropped loudly, not rerouted
        assert fan(0, b"outage2") == 0
        # per-container counter, once-per-episode flight event
        assert fails.value(dataset="allterm",
                           node="(all-terminal)") == before + 2
        assert ev_count() == ev_before + 1
        # a copy rejoins: delivery resumes and the episode re-arms
        m.update_status(0, ShardStatus.ASSIGNED, node="a")
        assert fan(0, b"back") == 1
        m.update_status(0, ShardStatus.DOWN, node="a")
        assert fan(0, b"outage3") == 0
        assert ev_count() == ev_before + 2
        assert fan.drain(timeout_s=10)
        assert got["a"] == [b"back"] and not got["b"]
        assert got["c"] == [b"boot"]

    def test_close_stops_peer_lanes(self):
        """Regression (review): FiloServer.shutdown closes the fanout —
        a 'killed' in-process node must not keep delivering buffered
        containers to surviving peers from beyond the grave."""
        from filodb_tpu.gateway.server import ReplicaFanout
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        gate = threading.Event()
        got = []

        def slow_peer(shard, container):
            gate.wait(5)
            got.append(container)

        fan = ReplicaFanout("closer", m,
                            {"a": lambda s, c: None, "b": slow_peer},
                            local_node="a")
        for i in range(8):
            fan(0, b"c%d" % i)             # b's lane buffers behind gate
        lane_threads = [ln._thread for ln in fan._lanes.values()]
        fan.close()
        gate.set()
        for t in lane_threads:
            t.join(timeout=5)
        assert all(not t.is_alive() for t in lane_threads)
        # at most the single in-flight delivery landed; the queued rest
        # were dropped by close(), and post-close publishes are refused
        assert len(got) <= 1
        assert fan(0, b"late") == 0

    def test_wedged_peer_never_stalls_the_gateway(self):
        """Regression (review, 2 rounds): a peer that blocks forever
        fills its own bounded lane and overflows — counted per container
        but flight-recorded only ONCE per episode (per-container events
        would evict every other diagnostic from the bounded ring during
        exactly the incident window) — while the gateway publish path
        and the local replica stay fast."""
        from filodb_tpu.gateway.server import ReplicaFanout
        from filodb_tpu.utils.devicewatch import FLIGHT
        fails = REGISTRY.counter(
            "filodb_ingest_replica_publish_failures_total")
        before = fails.value(dataset="wedge", node="b")
        ev_count = lambda: len(
            [e for e in FLIGHT.events(kind="ingest.replica_publish_failed")
             if e.get("dataset") == "wedge" and e.get("node") == "b"])
        ev_before = ev_count()
        m = ShardMapper(1, replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        wedge = threading.Event()
        got = []

        def stuck(shard, container):
            wedge.wait()                   # a peer that never answers

        fan = ReplicaFanout("wedge", m,
                            {"a": lambda s, c: got.append(c), "b": stuck},
                            local_node="a", max_queued_per_peer=4)
        t0 = time.perf_counter()
        for i in range(20):
            fan(0, b"c%d" % i)
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, f"gateway stalled {elapsed:.1f}s on a " \
                              f"wedged peer"
        assert len(got) == 20              # local replica got everything
        # overflow drops were counted loudly (lane bound 4 + 1 in-flight)
        assert fails.value(dataset="wedge", node="b") >= before + 10
        # ... but ONE flight event for the whole episode
        assert ev_count() == ev_before + 1
        # peer unwedges and drains: the successful deliveries re-arm
        # the SAME fanout's episode, so the next outage records again
        wedge.set()
        assert fan.drain(timeout_s=10)
        wedge.clear()
        fails2 = fails.value(dataset="wedge", node="b")
        for i in range(10):                # lane bound 4 + 1 in-flight
            fan(0, b"d%d" % i)
        assert fails2 < fails.value(dataset="wedge", node="b")
        assert ev_count() == ev_before + 2
        wedge.set()
        fan.close()

    def test_generative_dual_written_replicas_converge(self):
        """Generative sweep (satellite): random series/label churn
        dual-written through the fanout leaves both replicas with
        IDENTICAL index cardinality snapshots."""
        from filodb_tpu.gateway.server import ReplicaFanout, ShardingPublisher
        rng = np.random.default_rng(1234)
        m = ShardMapper(4, replication_factor=2)
        m.register_node([0, 1, 2, 3], "a")
        m.register_node([0, 1, 2, 3], "b")
        stores, per_node = _mk_stores(m, ("a", "b"))
        pub = ShardingPublisher(DEFAULT_SCHEMAS["gauge"], m,
                                ReplicaFanout("prom", m, per_node,
                                              local_node="a"),
                                spread=1)
        metrics = [f"gen_m{k}" for k in range(7)]
        for _round in range(20):
            for _ in range(int(rng.integers(5, 40))):
                tags = {"instance": f"i{int(rng.integers(0, 50))}",
                        "zone": f"z{int(rng.integers(0, 4))}",
                        "_ws_": "w", "_ns_": f"App-{int(rng.integers(0, 3))}"}
                pub.add_sample(str(rng.choice(metrics)), tags,
                               BASE + int(rng.integers(0, 10_000_000)),
                               float(rng.random()))
            pub.flush()
        assert pub.publish.drain(timeout_s=10)
        snaps = {}
        for node in ("a", "b"):
            snaps[node] = [stores[node].get_shard("prom", s)
                           .index.cardinality_snapshot()
                           for s in range(4)]
        assert snaps["a"] == snaps["b"]
        total = sum(active for active, _ in snaps["a"])
        assert total > 0


class TestContainerPushEdge:
    def test_http_push_lands_on_the_peer_stream(self):
        from filodb_tpu.gateway.server import http_container_push
        from filodb_tpu.http.server import FiloHttpServer
        from filodb_tpu.ingest.stream import QueueStreamFactory
        factory = QueueStreamFactory()
        srv = FiloHttpServer()
        srv.ingest_sink = lambda ds, shard, c: \
            factory.stream_for(ds, shard).push(c)
        port = srv.start()
        try:
            push = http_container_push(f"http://127.0.0.1:{port}", "prom")
            push(1, b"\x01container-bytes")
            stream = factory.stream_for("prom", 1)
            assert stream.end_offset() == 1
            # unknown routes 404 / empty bodies 400, loudly
            import urllib.error
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/ingest/prom/1", data=b"",
                method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=5)
            assert e.value.code == 400
        finally:
            srv.shutdown()

    def test_push_to_sinkless_server_is_404(self):
        from filodb_tpu.gateway.server import http_container_push
        from filodb_tpu.http.server import FiloHttpServer
        import urllib.error
        srv = FiloHttpServer()
        port = srv.start()
        try:
            push = http_container_push(f"http://127.0.0.1:{port}", "prom")
            with pytest.raises(urllib.error.HTTPError) as e:
                push(0, b"x")
            assert e.value.code == 404
        finally:
            srv.shutdown()

    def test_push_offsets_fast_forward_past_checkpoints(self):
        """Regression (review): a peer container pushed BEFORE the
        restarted consumer fast-forwards its queue must still be
        numbered above the recovery checkpoints — an offset below the
        group watermark would be silently skipped as already
        persisted, losing brand-new data."""
        from filodb_tpu.standalone import FiloServer
        srv = FiloServer({"node": "cpf", "datasets": []})
        srv.metastore.initialize()
        srv.manager.setup_dataset("cp", 2, 1)
        srv._queue_push_datasets.add("cp")
        for g in range(4):
            srv.metastore.write_checkpoint("cp", 0, g, 500)
        off = srv._ingest_push("cp", 0, b"fresh-container")
        assert off >= 501, off
        # and the floor is applied before the FIRST push only once
        assert srv._ingest_push("cp", 0, b"next") == off + 1
        # out-of-range shards are refused, never ACKed into a
        # consumerless queue (review regression)
        with pytest.raises(ValueError, match="out of range"):
            srv._ingest_push("cp", 9999, b"lost-forever")

    def test_push_floor_not_cached_on_transient_metastore_failure(self):
        """Regression (review): a checkpoint read failing during the
        first push (metastore not ready at restart) must NOT cache a
        floor of 0 — the fast-forward protection has to recover on the
        next push once the metastore is readable."""
        from filodb_tpu.standalone import FiloServer
        srv = FiloServer({"node": "cpf2", "datasets": []})
        srv.metastore.initialize()
        srv.manager.setup_dataset("cq", 1, 1)
        srv._queue_push_datasets.add("cq")
        for g in range(4):
            srv.metastore.write_checkpoint("cq", 0, g, 500)
        real = srv.metastore.read_checkpoints
        calls = {"n": 0}

        def flaky(ds, shard):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("meta store not ready")
            return real(ds, shard)

        srv.metastore.read_checkpoints = flaky
        srv._ingest_push("cq", 0, b"early")  # read failed: floor 0 ...
        assert ("cq", 0) not in srv._push_offset_floor  # ... NOT cached
        off = srv._ingest_push("cq", 0, b"late")  # retried, caught up
        assert off >= 501, off
        assert srv._push_offset_floor[("cq", 0)] == 501
        """Two FiloServer nodes, NO broker: rf=2 over the in-proc queue
        transport dual-writes every gateway container to the peer via
        the POST /ingest edge — both replicas end with the same rows."""
        import socket

        from filodb_tpu.standalone import FiloServer

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        ports = {"qa-a": free_port(), "qa-b": free_port()}
        peers = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
        servers = {}
        try:
            for n in ("qa-a", "qa-b"):
                servers[n] = FiloServer({
                    "node": n, "http-port": ports[n], "peers": peers,
                    "status-poll-interval-s": 0.2,
                    "datasets": [{"name": "qd", "num-shards": 2,
                                  "min-num-nodes": 2,
                                  "replication-factor": 2,
                                  "schema": "gauge", "spread": 1}]})
                servers[n].start()
            deadline = time.time() + 30
            m = servers["qa-a"].manager.mapper("qd")
            while time.time() < deadline:
                if all(len(m.live_replicas(s)) == 2 for s in range(2)) \
                        and all(r.status is ShardStatus.ACTIVE
                                for s in range(2)
                                for r in m.live_replicas(s)):
                    break
                time.sleep(0.05)
            assert all(len(m.live_replicas(s)) == 2 for s in range(2))
            pub = servers["qa-a"].write_publishers["qd"]
            from filodb_tpu.gateway.server import ReplicaFanout
            assert isinstance(pub.publish, ReplicaFanout)
            for i in range(40):
                pub.add_sample("dw_m", {"instance": f"i{i}", "_ws_": "w",
                                        "_ns_": "n"}, BASE + i * 1000,
                               float(i))
            pub.flush()
            deadline = time.time() + 20
            while time.time() < deadline:
                rows = [sum(sh.stats.rows_ingested
                            for sh in servers[n].memstore.shards("qd"))
                        for n in ("qa-a", "qa-b")]
                if rows[0] >= 40 and rows[1] >= 40:
                    break
                time.sleep(0.05)
            assert rows[0] >= 40 and rows[1] >= 40, \
                f"dual-write did not reach both replicas: {rows}"
        finally:
            for srv in servers.values():
                srv.shutdown()


# ---------------------------------------------------------------------------
# Recovery promotion: group head + evict/purge races
# ---------------------------------------------------------------------------


def _container(i, metric="rec_m", n_inst=13):
    b = RecordBuilder(DEFAULT_SCHEMAS["gauge"], container_size=1 << 14)
    b.add(BASE + i * 1000, [float(i)],
          {"__name__": metric, "u": f"s{i % n_inst}", "_ws_": "w",
           "_ns_": "n"})
    (out,) = b.containers()
    return out


class TestGroupHeadPromotion:
    def test_recovery_holds_until_group_head_reached(self):
        factory = QueueStreamFactory()
        store = TimeSeriesMemStore()
        store.setup("prom", DEFAULT_SCHEMAS, 0)
        for g in range(store.get_shard("prom", 0).num_groups):
            store.meta.write_checkpoint("prom", 0, g, 5)
        stream = factory.stream_for("prom", 0)
        for i in range(10):                       # offsets 0..9
            stream.push(_container(i))
        head = {"v": 14}
        events = []
        ic = IngestionCoordinator(
            "n", "prom", DEFAULT_SCHEMAS, store, factory,
            event_sink=events.append, recovery_report_interval=1,
            group_head_fn=lambda shard: head["v"])
        ic.start_ingestion(0)
        deadline = time.time() + 5
        while time.time() < deadline:
            if store.get_shard("prom", 0).latest_offset >= 9:
                break
            time.sleep(0.01)
        time.sleep(0.05)
        from filodb_tpu.coordinator.cluster import (IngestionStarted,
                                                    RecoveryInProgress)
        # consumed past the LOCAL checkpoint head (5) but the group head
        # (14) is ahead: the replica must still be recovering
        assert not any(isinstance(e, IngestionStarted) for e in events)
        assert any(isinstance(e, RecoveryInProgress) and 0 < e.progress_pct
                   for e in events)
        for i in range(10, 15):                   # offsets 10..14 = head
            stream.push(_container(i))
        deadline = time.time() + 5
        while time.time() < deadline:
            if any(isinstance(e, IngestionStarted) for e in events):
                break
            time.sleep(0.01)
        assert any(isinstance(e, IngestionStarted) for e in events), \
            "never promoted after reaching the group head"
        ic.stop_all()

    def test_promotion_races_concurrent_evict_and_purge(self):
        """Satellite edge: recovery replay with concurrent evict/purge
        churn must neither wedge promotion nor corrupt the index."""
        factory = QueueStreamFactory()
        store = TimeSeriesMemStore()
        store.setup("prom", DEFAULT_SCHEMAS, 0)
        for g in range(store.get_shard("prom", 0).num_groups):
            store.meta.write_checkpoint("prom", 0, g, 10)
        stream = factory.stream_for("prom", 0)
        n = 300
        for i in range(n):
            stream.push(_container(i, n_inst=37))
        events = []
        ic = IngestionCoordinator(
            "n", "prom", DEFAULT_SCHEMAS, store, factory,
            event_sink=events.append, recovery_report_interval=5,
            group_head_fn=lambda shard: n - 1)
        stop = threading.Event()
        churn_errors = []

        def churn():
            sh = store.get_shard("prom", 0)
            while not stop.is_set():
                try:
                    sh.evict_partitions(2)
                    sh.purge_expired(retention_ms=1,
                                     now_ms=BASE + 10_000_000_000)
                except Exception as e:  # noqa: BLE001
                    churn_errors.append(e)
                time.sleep(0.001)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        ic.start_ingestion(0)
        from filodb_tpu.coordinator.cluster import IngestionStarted
        deadline = time.time() + 20
        while time.time() < deadline:
            if any(isinstance(e, IngestionStarted) for e in events):
                break
            time.sleep(0.01)
        stop.set()
        t.join(timeout=5)
        ic.stop_all()
        assert not churn_errors, churn_errors
        assert any(isinstance(e, IngestionStarted) for e in events), \
            "promotion wedged by concurrent evict/purge"
        sh = store.get_shard("prom", 0)
        active, by_label = sh.index.cardinality_snapshot()
        assert active == sh.index.active_series_count()


# ---------------------------------------------------------------------------
# /admin/shards per-replica view
# ---------------------------------------------------------------------------


class TestAdminShardsReplicaView:
    def test_rows_list_replica_node_status_and_lag(self):
        from filodb_tpu.memstore.watermarks import WatermarkLedger
        m = ShardMapper(1, dataset="admrep", replication_factor=2)
        m.register_node([0], "a")
        m.register_node([0], "b")
        m.update_status(0, ShardStatus.ACTIVE, node="a")
        m.update_status(0, ShardStatus.RECOVERY, progress=60, node="b")
        m.note_watermark(0, "a", 1000)
        m.note_watermark(0, "b", 400)
        store = TimeSeriesMemStore()
        store.setup("admrep", DEFAULT_SCHEMAS, 0)
        ledger = WatermarkLedger(node="a")
        ledger.watch("admrep", store, mapper=m)
        tree = ledger.sample()
        row = tree["datasets"]["admrep"]["shards"][0]
        reps = {r["node"]: r for r in row["replicas"]}
        assert reps["a"]["status"] == "Active"
        assert reps["a"]["lag_rows"] == 0
        assert reps["b"]["status"] == "Recovery"
        assert reps["b"]["recovery_progress"] == 60
        assert reps["b"]["lag_rows"] == 600
        # shard-level fields show the SERVING view (review regression):
        # a dead PRIMARY must not report a served shard as down
        m.update_status(0, ShardStatus.DOWN, node="a")
        m.update_status(0, ShardStatus.ACTIVE, node="b")
        tree = ledger.sample()
        row = tree["datasets"]["admrep"]["shards"][0]
        assert row["status"] == "Active"
        assert row["queryable"] is True
        assert row["owner"] == "b"
        assert tree["datasets"]["admrep"]["totals"]["queryable"] == 1
