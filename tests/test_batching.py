"""Fleet batching tier (ISSUE 20): vmapped execution of concurrent
shape-compatible queries.

The load-bearing assertion is the generative bit-equality sweep:
results served from a stacked (vmapped) device launch are BIT-equal
(``tobytes``) to the solo per-query launches they replace, across
seeds x window functions x group sizes (including non-power-of-two
groups that exercise the padding path).  Plus: admission/deadline
discipline at stack time (mixed-deadline groups, mid-batch expiry),
the breaker-trip demotion ladder, and the disabled-by-config true
passthrough."""

import threading
import time

import numpy as np
import pytest

from filodb_tpu.batching import (QueryBatcher, batching_broken,
                                 reset_batch_breaker)
from filodb_tpu.batching.batcher import _Group, _Member, _pad_pow2
from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.logical import RangeFunctionId as F
from filodb_tpu.query.model import QueryContext
from filodb_tpu.utils.observability import batch_metrics

STEP = 60_000
T0 = 1_700_000_040_000
WINDOW = 300_000
K = WINDOW // STEP


@pytest.fixture(autouse=True)
def _closed_breaker():
    reset_batch_breaker()
    yield
    reset_batch_breaker()


def _mk_shard(n_series=6, n_rows=50, jitter_max=30_000, seed=0):
    ms = TimeSeriesMemStore()
    shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
    rng = np.random.default_rng(seed)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    for i in range(n_series):
        tags = {"__name__": "req_total", "instance": f"i{i}",
                "_ws_": "w", "_ns_": "n"}
        base = T0 + np.arange(n_rows, dtype=np.int64) * STEP - STEP + 1
        ts = base + rng.integers(0, max(jitter_max, 1), size=n_rows)
        vals = np.cumsum(rng.random(n_rows) * 5)
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    shard.flush_all()
    return ms, shard


def _part_ids(shard):
    return shard.lookup_partitions(
        [ColumnFilter("_metric_", Equals("req_total"))], 0, 2**62).part_ids


def _concurrent(shard, part_ids, func, starts, nsteps):
    """Fire one scan_grid per start from barrier-released threads;
    returns {start_index: values array}."""
    barrier = threading.Barrier(len(starts))
    outs: dict = {}
    errs: list = []

    def worker(i, s0):
        try:
            barrier.wait()
            got = shard.scan_grid(part_ids, func, s0, nsteps, STEP,
                                  WINDOW)
            outs[i] = None if got is None else np.asarray(got[1])
        except Exception as e:       # surfaced by the caller
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i, s0))
          for i, s0 in enumerate(starts)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    return outs


# ---------------------------------------------------------------------------
# the generative bit-equality sweep
# ---------------------------------------------------------------------------

SWEEP_FUNCS = [F.RATE, F.INCREASE, F.SUM_OVER_TIME, F.MAX_OVER_TIME]
SWEEP_SIZES = [2, 3, 8]          # 3 exercises the pad-to-power-of-two path


@pytest.mark.parametrize("seed", range(3))
def test_generative_bit_equality_sweep(seed):
    ms, shard = _mk_shard(seed=seed, jitter_max=1 + seed * 15_000)
    pids = _part_ids(shard)
    steps0 = T0 + (K - 1) * STEP
    # one more concurrent query than max_batch: a cold key bootstraps
    # off the overlap (passthrough + leader + joiners), so the first
    # group forms without any prior hotness
    n_conc = max(SWEEP_SIZES) + 1
    nsteps = 50 - K - n_conc
    for func in SWEEP_FUNCS:
        starts = [steps0 + i * STEP for i in range(n_conc)]
        # solo oracle: no batcher attached — today's per-query chain
        shard.query_batcher = None
        solos = {}
        for i, s0 in enumerate(starts):
            got = shard.scan_grid(pids, func, s0, nsteps, STEP, WINDOW)
            assert got is not None, f"grid declined func={func}"
            solos[i] = np.asarray(got[1])
        for size in SWEEP_SIZES:
            bat = QueryBatcher(enabled=True, window_ms=150.0,
                               max_batch=size, hot_ttl_s=30.0,
                               dataset="prom")
            shard.query_batcher = bat
            nq = size + 1
            # the bootstrap overlap is scheduling-dependent, so round
            # until a group forms; bit-equality must hold on EVERY
            # round, grouped or not
            for _round in range(12):
                outs = _concurrent(shard, pids, func, starts[:nq],
                                   nsteps)
                for i in range(nq):
                    assert outs[i] is not None
                    assert outs[i].tobytes() == solos[i].tobytes(), \
                        f"seed={seed} func={func} size={size} " \
                        f"member={i} round={_round}: batched result " \
                        f"differs from solo"
                if bat.snapshot()["realized_peak"] >= 2 and _round:
                    break       # grouped round verified bit-equal
            assert bat.snapshot()["realized_peak"] >= 2, \
                f"seed={seed} func={func} size={size}: no group formed"
    shard.query_batcher = None


def test_grouped_agg_batched_bit_equal():
    ms, shard = _mk_shard(n_series=8)
    pids = _part_ids(shard)
    steps0 = T0 + (K - 1) * STEP
    nsteps = 50 - K - 4
    gids = list(range(len(pids)))
    starts = [steps0 + i * STEP for i in range(4)]

    def run(s0):
        return shard.scan_grid_grouped(pids, F.RATE, s0, nsteps, STEP,
                                       WINDOW, gids, len(pids), "sum")

    shard.query_batcher = None
    solos = [run(s0) for s0 in starts]
    assert all(s is not None for s in solos)
    bat = QueryBatcher(enabled=True, window_ms=500.0, max_batch=4,
                       hot_ttl_s=30.0, dataset="prom")
    shard.query_batcher = bat
    outs: dict = {}
    for _round in range(12):
        outs.clear()
        barrier = threading.Barrier(len(starts))

        def worker(i, s0):
            barrier.wait()
            outs[i] = run(s0)

        ts = [threading.Thread(target=worker, args=(i, s0))
              for i, s0 in enumerate(starts)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if bat.snapshot()["realized_peak"] >= 2 and _round:
            break
    assert bat.snapshot()["realized_peak"] >= 2
    for i, solo in enumerate(solos):
        got = outs[i]
        assert set(got) == set(solo)
        for op in solo:
            assert np.asarray(got[op]).tobytes() == \
                np.asarray(solo[op]).tobytes(), f"member={i} op={op}"
    shard.query_batcher = None


# ---------------------------------------------------------------------------
# config / passthrough
# ---------------------------------------------------------------------------


def test_disabled_by_config_is_true_passthrough():
    ms, shard = _mk_shard()
    pids = _part_ids(shard)
    steps0 = T0 + (K - 1) * STEP
    nsteps = 50 - K - 4
    starts = [steps0 + i * STEP for i in range(4)]
    shard.query_batcher = None
    solos = {i: np.asarray(shard.scan_grid(pids, F.RATE, s0, nsteps,
                                           STEP, WINDOW)[1])
             for i, s0 in enumerate(starts)}
    groups0 = batch_metrics()["groups"].total()
    bat = QueryBatcher(enabled=False, window_ms=500.0, max_batch=4,
                       dataset="prom")
    shard.query_batcher = bat
    for _ in range(2):
        outs = _concurrent(shard, pids, F.RATE, starts, nsteps)
    for i in range(4):
        assert outs[i].tobytes() == solos[i].tobytes()
    assert bat.snapshot()["realized_peak"] == 0
    assert not bat._groups and not bat._hot and not bat._inflight
    assert batch_metrics()["groups"].total() == groups0, \
        "disabled batcher must form no groups"
    # runtime re-enable via the same configure() the admin knob calls
    bat.configure(enabled=True)
    for _round in range(12):
        outs = _concurrent(shard, pids, F.RATE, starts, nsteps)
        for i in range(4):
            assert outs[i].tobytes() == solos[i].tobytes()
        if bat.snapshot()["realized_peak"] >= 2 and _round:
            break
    assert bat.snapshot()["realized_peak"] >= 2
    shard.query_batcher = None


# ---------------------------------------------------------------------------
# admission / deadline discipline (unit level on QueryBatcher)
# ---------------------------------------------------------------------------


class _FakePermit:
    def __init__(self, released=False):
        self.released = released


def _stack_launch(row0s, steps0s):
    """Synthetic stacked launch: member axis leading, value encodes
    (row0, steps0) so fan-out mistakes are visible."""
    return np.asarray([[r * 1000 + s] for r, s in
                       zip(np.asarray(row0s), np.asarray(steps0s))],
                      dtype=np.float64)


def _qctx_with(deadline_in_ms=None, permit=None):
    qc = QueryContext()
    if deadline_in_ms is not None:
        qc.deadline_ms = int(time.time() * 1000) + deadline_in_ms
    if permit is not None:
        qc.admission_permit = permit
    return qc


def test_pad_pow2():
    assert [_pad_pow2(n, 8) for n in (1, 2, 3, 5, 8, 9)] == \
        [1, 2, 4, 8, 8, 8]


def test_mixed_deadline_group_stacks_all_live_members():
    bat = QueryBatcher(enabled=True, window_ms=50.0, max_batch=8,
                       dataset="unit")
    g = _Group("k")
    # three live members with very different (but sufficient) budgets
    g.members = [_Member(1, 10, _qctx_with(deadline_in_ms=60_000)),
                 _Member(2, 20, _qctx_with(deadline_in_ms=600_000)),
                 _Member(3, 30, _qctx_with())]       # no deadline at all
    bat._launch_group(g, _stack_launch)
    assert [None if r is None else float(r[0]) for r in g.results] == \
        [1010.0, 2020.0, 3030.0]


def test_mid_batch_expiry_drops_members_from_the_stack():
    bat = QueryBatcher(enabled=True, window_ms=50.0, max_batch=8,
                       dataset="unit")
    fb0 = batch_metrics()["fallbacks"].total()
    g = _Group("k")
    g.members = [
        _Member(1, 10, _qctx_with(deadline_in_ms=60_000)),
        # permit released while the window was open
        _Member(2, 20, _qctx_with(permit=_FakePermit(released=True))),
        # deadline died while the window was open
        _Member(3, 30, _qctx_with(deadline_in_ms=-5)),
        _Member(4, 40, _qctx_with(deadline_in_ms=60_000)),
    ]
    bat._launch_group(g, _stack_launch)
    assert g.results[0] is not None and g.results[3] is not None
    assert g.results[1] is None and g.results[2] is None, \
        "expired members must be dropped from the stack"
    assert float(g.results[0][0]) == 1010.0
    assert float(g.results[3][0]) == 4040.0
    assert batch_metrics()["fallbacks"].total() == fb0 + 2


def test_group_of_expired_members_demotes_without_launching():
    bat = QueryBatcher(enabled=True, window_ms=50.0, max_batch=8,
                       dataset="unit")
    launched = []
    g = _Group("k")
    g.members = [_Member(1, 10, _qctx_with(deadline_in_ms=-5)),
                 _Member(2, 20, _qctx_with(deadline_in_ms=60_000))]
    bat._launch_group(g, lambda r, s: launched.append(1))
    assert g.results is None and not launched, \
        "<2 live members: the group demotes, nothing launches"


def test_short_deadline_joins_no_batch():
    bat = QueryBatcher(enabled=True, window_ms=100.0, max_batch=8,
                       dataset="unit")
    fb0 = batch_metrics()["fallbacks"].total()
    # remaining budget (40ms) cannot afford window (100ms) + slack
    got = bat.dispatch("k", 1, 10, _qctx_with(deadline_in_ms=40),
                       _stack_launch, lambda: "solo")
    assert got is None, "caller must run its own solo fallback"
    assert batch_metrics()["fallbacks"].total() == fb0 + 1


def test_cold_key_is_passthrough_solo():
    bat = QueryBatcher(enabled=True, window_ms=200.0, max_batch=8,
                       dataset="unit")
    t0 = time.monotonic()
    got = bat.dispatch("k", 1, 10, None, _stack_launch, lambda: "solo")
    assert got == "solo"
    assert time.monotonic() - t0 < 0.15, \
        "a cold key must not wait out the co-arrival window"
    assert not bat._groups


def test_solo_window_leader_falls_back():
    bat = QueryBatcher(enabled=True, window_ms=30.0, max_batch=8,
                       dataset="unit")
    fb0 = batch_metrics()["fallbacks"].total()
    bat._hot["k"] = time.monotonic() + 100.0     # force leading
    got = bat.dispatch("k", 1, 10, None, _stack_launch, lambda: "solo")
    assert got is None, "window expired alone: caller runs solo"
    assert batch_metrics()["fallbacks"].total() == fb0 + 1


def test_concurrent_twins_form_a_group():
    bat = QueryBatcher(enabled=True, window_ms=400.0, max_batch=2,
                       dataset="unit")
    bat._hot["k"] = time.monotonic() + 100.0
    outs: dict = {}
    barrier = threading.Barrier(2)

    def worker(i):
        barrier.wait()
        outs[i] = bat.dispatch("k", i + 1, (i + 1) * 10, None,
                               _stack_launch, lambda: "solo")

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    got = sorted(float(v[0]) for v in outs.values() if v is not None)
    assert got == [1010.0, 2020.0]
    assert bat.snapshot()["realized_peak"] == 2


# ---------------------------------------------------------------------------
# breaker ladder
# ---------------------------------------------------------------------------


def test_breaker_trip_demotes_group_and_opens_breaker():
    bat = QueryBatcher(enabled=True, window_ms=400.0, max_batch=2,
                       dataset="unit")
    bat._hot["k"] = time.monotonic() + 100.0
    fb0 = batch_metrics()["fallbacks"].total()

    def boom(row0s, steps0s):
        raise RuntimeError("vmapped program exploded")

    outs: dict = {}
    barrier = threading.Barrier(2)

    def worker(i):
        barrier.wait()
        outs[i] = bat.dispatch("k", i + 1, (i + 1) * 10, None, boom,
                               lambda: "solo")

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # the whole group demoted: every member's caller runs its solo
    assert list(outs.values()) == [None, None]
    assert batching_broken(), "a batched-path error must open the breaker"
    assert batch_metrics()["fallbacks"].total() >= fb0 + 2
    assert bat.snapshot()["breaker_open"]
    # while open, every dispatch is an immediate fallback
    assert bat.dispatch("k", 9, 90, None, _stack_launch,
                        lambda: "solo") is None
    reset_batch_breaker()
    assert not batching_broken()
    got = bat.dispatch("k2", 1, 10, None, _stack_launch, lambda: "solo")
    assert got == "solo"     # cold key passthrough works again


def test_breaker_trip_end_to_end_serves_solo(monkeypatch):
    """A failing vmapped device program must demote to the per-query
    chain and serve bytes identical to an unbatched serve."""
    ms, shard = _mk_shard()
    pids = _part_ids(shard)
    steps0 = T0 + (K - 1) * STEP
    nsteps = 50 - K - 4
    starts = [steps0 + i * STEP for i in range(4)]
    shard.query_batcher = None
    solos = {i: np.asarray(shard.scan_grid(pids, F.RATE, s0, nsteps,
                                           STEP, WINDOW)[1])
             for i, s0 in enumerate(starts)}
    from filodb_tpu.memstore import devicestore as dvs
    dvs._fused_progs()          # populate the program memo first

    def boom(*a, **kw):
        raise RuntimeError("batched program failure injected")

    monkeypatch.setitem(dvs._FUSED_PROGS, "series_batch", boom)
    bat = QueryBatcher(enabled=True, window_ms=500.0, max_batch=4,
                       hot_ttl_s=30.0, dataset="prom")
    shard.query_batcher = bat
    for _ in range(2):
        outs = _concurrent(shard, pids, F.RATE, starts, nsteps)
    for i in range(4):
        assert outs[i] is not None
        assert outs[i].tobytes() == solos[i].tobytes(), \
            f"member {i}: demoted result differs from solo"
    shard.query_batcher = None
