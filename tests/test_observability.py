"""Metrics primitives, exposition-format correctness, trace forensics.

ISSUE 2 satellites: Prometheus text-exposition grammar + histogram
invariants, the Gauge set_fn-under-lock deadlock regression, scheduler
saturation metrics, and the TraceStore/slow-log/profiler units."""

import re
import threading
import time

import pytest

from filodb_tpu.utils.forensics import (TraceStore, profile, span_from_dict,
                                        span_to_dict)
from filodb_tpu.utils.observability import (REGISTRY, MetricsRegistry,
                                            SpanRecord, Tracer)

# ---------------------------------------------------------------------------
# Exposition-format grammar (satellite: line-by-line correctness)
# ---------------------------------------------------------------------------

_COMMENT_RE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$")
_METRIC_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? "
    r"(?P<value>NaN|[+-]Inf|-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')


def _assert_exposition_valid(text: str) -> None:
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert _COMMENT_RE.match(line), f"bad comment line: {line!r}"
            continue
        m = _METRIC_RE.match(line)
        assert m, f"line does not match exposition grammar: {line!r}"
        labels = m.group("labels")
        if labels is not None:
            # every byte of the label block must be consumed by
            # well-formed name="escaped-value" pairs
            rebuilt = ",".join(f'{k}="{v}"'
                               for k, v in _LABEL_RE.findall(labels))
            assert rebuilt == labels, f"malformed labels in: {line!r}"


class TestExposition:
    def test_label_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("esc_total")
        c.inc(path='with"quote', other="back\\slash", nl="a\nb")
        text = reg.expose_text()
        _assert_exposition_valid(text)
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        # no RAW newline inside any metric line
        for line in text.splitlines():
            assert "\n" not in line

    def test_full_registry_parses(self):
        # the PROCESS registry: whatever every subsystem registered must
        # come out grammatically valid, line by line
        REGISTRY.counter("exp_probe_total").inc(dataset="p", weird='q"x')
        REGISTRY.histogram("exp_probe_seconds").observe(0.2, lane="a\\b")
        _assert_exposition_valid(REGISTRY.expose_text())

    def test_histogram_invariants(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.001, 0.01, 0.05, 0.1, 0.5, 2.0, 100.0):
            h.observe(v, op="x")
        lines = reg.expose_text().splitlines()
        buckets = {}
        count = total_sum = None
        for ln in lines:
            m = _METRIC_RE.match(ln)
            if not m:
                continue
            if m.group("name") == "lat_seconds_bucket":
                le = dict(_LABEL_RE.findall(m.group("labels")))["le"]
                buckets[le] = float(m.group("value"))
            elif m.group("name") == "lat_seconds_count":
                count = float(m.group("value"))
            elif m.group("name") == "lat_seconds_sum":
                total_sum = float(m.group("value"))
        # le="b" means value <= b: boundary observations fall IN bucket
        assert buckets["0.01"] == 2          # 0.001, 0.01
        assert buckets["0.1"] == 4           # + 0.05, 0.1
        assert buckets["1.0"] == 5           # + 0.5
        assert buckets["+Inf"] == 7
        # cumulative monotone + count == +Inf bucket
        seq = [buckets["0.01"], buckets["0.1"], buckets["1.0"],
               buckets["+Inf"]]
        assert seq == sorted(seq)
        assert count == buckets["+Inf"] == 7
        assert total_sum == pytest.approx(sum(
            (0.001, 0.01, 0.05, 0.1, 0.5, 2.0, 100.0)))

    def test_histogram_unsorted_buckets_normalized(self):
        reg = MetricsRegistry()
        h = reg.histogram("uns_seconds", buckets=(1.0, 0.1, 0.01))
        assert h.buckets == (0.01, 0.1, 1.0)
        h.observe(0.05)
        assert h._counts[()][1] == 1  # bisect lands in the 0.1 bucket


class TestGaugeLock:
    def test_set_fn_touching_gauge_does_not_deadlock(self):
        """Regression (satellite 1): expose()/total() used to call the
        registered set_fn callbacks while holding the gauge lock, so a
        callback touching the same gauge deadlocked the scrape."""
        reg = MetricsRegistry()
        g = reg.gauge("self_referential")

        def cb():
            g.set(5.0, which="side_effect")  # takes the gauge lock
            return 7.0

        g.set_fn(cb, which="cb")
        out = []

        def scrape():
            out.append(g.expose())
            out.append(g.total())

        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive(), \
            "gauge scrape deadlocked calling its own set_fn"
        assert out[1] == 7.0 + 5.0


class TestSchedulerSaturationMetrics:
    def test_queue_depth_gauge_and_rejection_counter(self):
        from filodb_tpu.query.scheduler import QueryRejected, QueryScheduler
        s = QueryScheduler(num_workers=1, max_queued=2, name="satsched")
        try:
            gate = threading.Event()
            started = threading.Event()
            s.submit(lambda: started.set() or gate.wait(5))
            started.wait(5)
            s.submit(lambda: 1)
            s.submit(lambda: 2)
            depth = REGISTRY.gauge("filodb_query_queue_depth")
            assert depth.value(scheduler="satsched") == 2
            rej = REGISTRY.counter("filodb_queries_rejected_total")
            before = rej.value(scheduler="satsched", reason="full")
            with pytest.raises(QueryRejected):
                s.submit(lambda: 3)
            assert rej.value(scheduler="satsched",
                             reason="full") == before + 1
            gate.set()
        finally:
            s.shutdown()
        # shutdown must deregister the depth callback: no row for a
        # dead scheduler, no bound method keeping it alive
        text = "\n".join(REGISTRY.gauge("filodb_query_queue_depth")
                         .expose())
        assert 'scheduler="satsched"' not in text


# ---------------------------------------------------------------------------
# Trace forensics
# ---------------------------------------------------------------------------


class TestTraceStore:
    def _traced(self, store, fn):
        tracer = Tracer()
        tracer.add_reporter(store.report)
        tid = tracer.new_trace_id()
        with tracer.attach((tid, None)):
            fn(tracer)
        return tid

    def test_tree_nesting_and_untraced_spans_skipped(self):
        store = TraceStore()

        def work(tracer):
            with tracer.span("root", dataset="p"):
                with tracer.span("child"):
                    pass
                with tracer.span("child2"):
                    pass

        tid = self._traced(store, work)
        # spans with no trace id never enter the store
        store.report(SpanRecord("orphan", 0, 0.1, {}, None))
        tree = store.tree(tid)
        assert len(tree) == 1 and tree[0]["name"] == "root"
        kids = [c["name"] for c in tree[0]["children"]]
        assert kids == ["child", "child2"]
        assert tid not in ("", None) and store.tree("nope") == []

    def test_slowlog_threshold(self):
        store = TraceStore(slow_threshold_s=0.5)
        tid = self._traced(
            store, lambda tr: tr.span("q").__enter__().__exit__(
                None, None, None))
        store.note_complete(tid, 0.1, query="fast")
        assert store.slowlog() == []
        store.note_complete(tid, 0.9, query="slow", dataset="prom")
        log = store.slowlog()
        assert len(log) == 1
        assert log[0]["query"] == "slow"
        assert log[0]["trace_id"] == tid
        assert log[0]["tree"] and log[0]["tree"][0]["name"] == "q"

    def test_ingest_remote_dedups_and_stitches(self):
        store = TraceStore()
        tid = "feedfeedfeedfeed"
        local = SpanRecord("dispatch.http", 0, 1.0, {}, None,
                           trace_id=tid, span_id="aaa")
        store.report(local)
        remote = [{"name": "execplan.execute", "start_s": 0.1,
                   "duration_s": 0.5, "tags": {"shard": "1"},
                   "trace_id": tid, "span_id": "bbb", "parent_id": "aaa"}]
        store.ingest_remote(tid, remote)
        store.ingest_remote(tid, remote)  # a second leaf returns it again
        spans = store.spans_for(tid)
        assert [r.span_id for r in spans] == ["aaa", "bbb"]
        tree = store.tree(tid)
        assert tree[0]["name"] == "dispatch.http"
        assert tree[0]["children"][0]["name"] == "execplan.execute"

    def test_bounded_traces(self):
        store = TraceStore(max_traces=4)
        for i in range(10):
            store.report(SpanRecord("s", 0, 0.1, {}, None,
                                    trace_id=f"t{i}", span_id=f"id{i}"))
        assert len(store.trace_ids()) == 4
        assert store.trace_ids()[-1] == "t9"

    def test_span_dict_roundtrip(self):
        rec = SpanRecord("n", 1.0, 2.0, {"a": 1}, None, error="E",
                         trace_id="t", span_id="s", parent_id="p")
        back = span_from_dict(span_to_dict(rec))
        assert back.name == "n" and back.trace_id == "t"
        assert back.span_id == "s" and back.parent_id == "p"
        assert back.error == "E" and back.tags == {"a": "1"}


def test_profile_returns_hot_frames():
    stop = threading.Event()

    def burn():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=burn, daemon=True)
    t.start()
    try:
        out = profile(seconds=0.15, sample_interval_s=0.002)
    finally:
        stop.set()
        t.join(1)
    assert out["samples"] >= 1
    assert out["frames"] and {"file", "function", "samples", "pct"} <= \
        set(out["frames"][0])


def test_tracer_ids_and_attach():
    tracer = Tracer()
    recs = []
    tracer.add_reporter(recs.append)
    tid = tracer.new_trace_id()
    with tracer.attach((tid, "parenthint")):
        with tracer.span("outer"):
            token = tracer.capture()
            with tracer.span("inner"):
                pass
    assert [r.name for r in recs] == ["inner", "outer"]
    inner, outer = recs
    assert outer.trace_id == inner.trace_id == tid
    assert outer.parent_id == "parenthint"  # hint parents the root span
    assert inner.parent_id == outer.span_id
    assert token == (tid, outer.span_id)
    # outside the attach the thread is clean again
    assert tracer.current_trace_id() is None
