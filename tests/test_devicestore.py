"""Device-resident chunk store: correctness + caching + fallback.

Proves the serving seam the reference places at block memory (queries
read from BlockManager-resident chunks, never re-copying them —
reference: memory/BlockManager.scala:142): the grid path must be
bit-consistent with the general scan path, must not rebuild blocks on a
repeat query (zero host->device transfer), must invalidate on new data,
and must fall back — never be wrong — on irregular layouts.
"""

import numpy as np
import pytest

from filodb_tpu.core.filters import ColumnFilter, Equals
from filodb_tpu.core.record import RecordBuilder, decode_container
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
from filodb_tpu.core.storeconfig import StoreConfig
from filodb_tpu.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.query.logical import RangeFunctionId as F

STEP = 60_000
# step-aligned in absolute ms: dashboards align query starts to the step
# grid, and the bucket-grid phase is anchored at absolute step multiples
T0 = 1_700_000_040_000
assert T0 % STEP == 0
WINDOW = 300_000
K = WINDOW // STEP


def _mk_shard(n_series=6, n_rows=50, jitter_max=30_000, seed=0,
              flush=True, **cfg_kw):
    ms = TimeSeriesMemStore()
    cfg = StoreConfig(**cfg_kw)
    shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, cfg)
    rng = np.random.default_rng(seed)
    b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
    truth = {}
    for i in range(n_series):
        tags = {"__name__": "req_total", "instance": f"i{i}", "_ws_": "w",
                "_ns_": "n"}
        base = T0 + np.arange(n_rows, dtype=np.int64) * STEP - STEP + 1
        ts = base + rng.integers(0, max(jitter_max, 1), size=n_rows)
        vals = np.cumsum(rng.random(n_rows) * 5)
        truth[f"i{i}"] = (ts, vals)
        for t, v in zip(ts, vals):
            b.add(int(t), [float(v)], tags)
    for off, c in enumerate(b.containers()):
        shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
    if flush:
        shard.flush_all()
    return ms, shard, truth


def _lookup(shard):
    return shard.lookup_partitions(
        [ColumnFilter("_metric_", Equals("req_total"))], 0, 2**62)


def _steps(n_rows):
    steps0 = T0 + (K - 1) * STEP
    nsteps = n_rows - K
    return steps0, nsteps


class TestDeviceGrid:
    def test_late_lane_partitions_rebuild_blocks(self):
        """A partition that gets its lane AFTER blocks were built (a
        second metric of the same schema, or a just-paged-in series)
        must trigger a block rebuild — its unstaged lanes would
        otherwise pass the dense proof as 'empty' and silently serve
        all-NaN for real data."""
        from filodb_tpu.core.filters import ColumnFilter, Equals
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        rng = np.random.default_rng(3)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        for metric in ("m_a", "m_b"):
            for i in range(3):
                tags = {"__name__": metric, "instance": f"i{i}",
                        "_ws_": "w", "_ns_": "n"}
                base = T0 + np.arange(50, dtype=np.int64) * STEP - STEP + 1
                vals = np.cumsum(rng.random(50) * 5)
                for t, v in zip(base, vals):
                    b.add(int(t), [float(v)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        steps0, nsteps = _steps(50)
        # metric A builds the blocks with only ITS lanes staged
        res_a = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("m_a"))], 0, 2**62)
        got_a = shard.scan_grid(res_a.part_ids, F.RATE, steps0, nsteps,
                                STEP, WINDOW)
        assert got_a is not None
        # metric B gets lanes AFTER the build: must serve real values
        res_b = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("m_b"))], 0, 2**62)
        got_b = shard.scan_grid(res_b.part_ids, F.RATE, steps0, nsteps,
                                STEP, WINDOW)
        assert got_b is not None
        _tags, vals_b, _ = got_b
        assert np.isfinite(vals_b).any(), \
            "late-lane metric served all-NaN from stale blocks"
        t2, batch = shard.scan_batch(res_b.part_ids, steps0 - WINDOW,
                                     steps0 + (nsteps - 1) * STEP)
        sr = StepRange(steps0, steps0 + (nsteps - 1) * STEP, STEP)
        oracle = np.asarray(rangefns.apply_range_function(
            batch, sr, WINDOW, F.RATE))
        np.testing.assert_allclose(vals_b, oracle[:len(vals_b)],
                                   rtol=1e-6, equal_nan=True)

    def test_matches_scan_batch_path(self):
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, truth = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None, "grid path should serve this query"
        tags, vals, _tops = got
        # general path oracle
        t2, batch = shard.scan_batch(res.part_ids, steps0 - WINDOW,
                                     steps0 + (nsteps - 1) * STEP)
        sr = StepRange(steps0, steps0 + (nsteps - 1) * STEP, STEP)
        want = np.asarray(rangefns.apply_range_function(
            batch, sr, WINDOW, F.RATE))[:len(t2)]   # drop series padding
        assert [t["instance"] for t in tags] == \
            [t["instance"] for t in t2]
        assert (np.isfinite(vals) == np.isfinite(want)).all()
        both = np.isfinite(vals)
        np.testing.assert_allclose(vals[both], want[both], rtol=1e-4)

    def test_repeat_query_zero_uploads(self):
        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        a = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP, WINDOW)
        cache = next(iter(shard.device_caches.values()))
        builds_after_first = cache.builds
        assert builds_after_first > 0
        b = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP, WINDOW)
        assert cache.builds == builds_after_first  # served from HBM
        np.testing.assert_array_equal(np.isfinite(a[1]), np.isfinite(b[1]))
        np.testing.assert_allclose(a[1][np.isfinite(a[1])],
                                   b[1][np.isfinite(b[1])])

    def test_new_ingest_refreshes_tail(self):
        ms, shard, truth = _mk_shard(n_rows=30, flush=False)
        res = _lookup(shard)
        steps0, nsteps = _steps(30)
        first = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                                WINDOW)
        assert first is not None
        # append one more sample to series i0 inside the last window
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        last_ts = int(truth["i0"][0][-1])
        b.add(last_ts + STEP, [truth["i0"][1][-1] + 100.0],
              {"__name__": "req_total", "instance": "i0", "_ws_": "w",
               "_ns_": "n"})
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), 1000 + off)
        steps0b = steps0 + STEP
        second = shard.scan_grid(res.part_ids, F.RATE, steps0b, nsteps, STEP,
                                 WINDOW)
        assert second is not None
        # the appended jump must be visible in the final windows
        assert not np.array_equal(first[1][:, -1], second[1][:, -1])

    def test_unaligned_step_falls_back(self):
        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        assert shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps,
                               STEP // 2, WINDOW) is None
        assert shard.scan_grid(res.part_ids, F.RATE, steps0 + 7, nsteps,
                               STEP, WINDOW) is None
        # argument-arity mismatch must decline, never mis-serve
        assert shard.scan_grid(res.part_ids, F.HOLT_WINTERS, steps0,
                               nsteps, STEP, WINDOW, fargs=(0.3,)) is None

    def test_flush_headroom_trims_below_budget(self):
        """The flush task proactively reclaims device blocks down to
        (1-headroom) of budget, so queries rarely pay inline eviction
        (reference: BlockManager ensureHeadroomPercentAvailable)."""
        ms, shard, _ = _mk_shard(n_rows=300, device_cache_bytes=300_000,
                                 device_headroom_frac=0.5)
        res = _lookup(shard)
        steps0, nsteps = _steps(300)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        resident_before = cache.bytes_resident
        assert resident_before > 0
        freed = cache.ensure_headroom(shard.config.device_headroom_frac)
        assert freed > 0
        assert cache.bytes_resident <= 300_000 * 0.5 + 1
        # the flush path drives it automatically
        shard.flush_all()
        assert cache.bytes_resident <= 300_000 * 0.5 + 1

    def test_dense_contract_detected(self):
        """Regular scrapes with no holes: the store proves the
        dense-lane contract from per-block fill ranges and dispatches
        the dense kernel (GridQuery.dense)."""
        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        assert cache.dense_hits == cache.hits > 0

    def test_gappy_series_uses_general_kernel(self):
        """A series with a missed scrape mid-range breaks the contract:
        the grid still serves (one-per-bucket holds) but via the general
        kernel, and the result still matches the dense shard's shape."""
        ms, shard, _ = _mk_shard(n_series=4, n_rows=50)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        tags = {"__name__": "req_total", "instance": "gappy", "_ws_": "w",
                "_ns_": "n"}
        for c in range(0, 50, 2):              # every other bucket
            b.add(T0 + (c - 1) * STEP + 10, [float(c)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), 700 + off)
        shard.flush_all()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits > 0 and cache.dense_hits == 0
        # the gappy lane still produces finite rates (2+ samples/window)
        tags_out, vals, _tops = got
        gi = next(i for i, t in enumerate(tags_out)
                  if t.get("instance") == "gappy")
        assert np.isfinite(vals[gi]).any()

    def test_coarser_step_served_with_stride(self):
        """A dashboard step of 2x the scrape cadence stays on the grid
        (stride serving) and matches the general scan path."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps_full = _steps(50)
        step2 = 2 * STEP
        nsteps = (nsteps_full + 1) // 2
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, step2,
                              WINDOW)
        assert got is not None, "strided grid should serve step=2*gstep"
        tags, vals, _tops = got
        assert vals.shape[1] == nsteps
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits > 0
        # oracle: general scan path on the same coarse step grid
        end = steps0 + (nsteps - 1) * step2
        t2, batch = shard.scan_batch(res.part_ids, steps0 - WINDOW, end)
        sr = StepRange(steps0, end, step2)
        want = np.asarray(rangefns.apply_range_function(
            batch, sr, WINDOW, F.RATE))[:len(tags)]
        got_v = np.asarray(vals)
        assert (np.isfinite(got_v) == np.isfinite(want)).all()
        fin = np.isfinite(want)
        assert fin.any()
        np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4)

    def test_large_window_served_when_dense(self):
        """K-free dense ops (rate) take windows beyond MAX_K_BUCKETS —
        a 2-hour lookback over 1m scrapes (K=120) stays on the fast
        path when the dense contract is proven."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, _ = _mk_shard(n_rows=200)
        res = _lookup(shard)
        big_w = 120 * STEP                     # K = 120 > 64
        steps0 = T0 + 120 * STEP
        nsteps = 40
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              big_w)
        assert got is not None, "dense large-K rate should serve"
        cache = next(iter(shard.device_caches.values()))
        assert cache.dense_hits > 0
        tags, vals, _tops = got
        end = steps0 + (nsteps - 1) * STEP
        t2, batch = shard.scan_batch(res.part_ids, steps0 - big_w, end)
        want = np.asarray(rangefns.apply_range_function(
            batch, StepRange(steps0, end, STEP), big_w,
            F.RATE))[:len(tags)]
        got_v = np.asarray(vals)
        assert (np.isfinite(got_v) == np.isfinite(want)).all()
        fin = np.isfinite(want)
        np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4)
        # sum_over_time accumulates K slices even when dense: capped
        assert shard.scan_grid(res.part_ids, F.SUM_OVER_TIME, steps0,
                               nsteps, STEP, big_w) is None

    def test_predict_linear_served_with_arg(self):
        """predict_linear carries its horizon through GridQuery.farg."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.PREDICT_LINEAR, steps0,
                              nsteps, STEP, WINDOW, fargs=(600.0,))
        assert got is not None
        tags, vals, _tops = got
        end = steps0 + (nsteps - 1) * STEP
        t2, batch = shard.scan_batch(res.part_ids, steps0 - WINDOW, end)
        want = np.asarray(rangefns.apply_range_function(
            batch, StepRange(steps0, end, STEP), WINDOW, F.PREDICT_LINEAR,
            (600.0,)))[:len(tags)]
        got_v = np.asarray(vals)
        fin = np.isfinite(want)
        assert fin.any()
        assert (np.isfinite(got_v) == fin).all()
        np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4)
        # missing the required arg: fall back, never mis-serve
        assert shard.scan_grid(res.part_ids, F.PREDICT_LINEAR, steps0,
                               nsteps, STEP, WINDOW) is None

    @pytest.mark.parametrize("func,wfn", [
        (F.STDDEV_OVER_TIME, "stddev_over_time"),
        (F.IRATE, "irate"), (F.CHANGES, "changes_over_time"),
        (F.DERIV, "deriv"), (F.Z_SCORE, "z_score"),
        (F.DELTA, "delta_fn"), (F.TIMESTAMP, "timestamp_fn")])
    def test_extended_ops_served_from_grid(self, func, wfn):
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, func, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None, f"{func} should serve from the grid"
        tags, vals, _tops = got
        end = steps0 + (nsteps - 1) * STEP
        t2, batch = shard.scan_batch(res.part_ids, steps0 - WINDOW, end)
        want = np.asarray(rangefns.apply_range_function(
            batch, StepRange(steps0, end, STEP), WINDOW, func))[:len(tags)]
        got_v = np.asarray(vals)
        assert (np.isfinite(got_v) == np.isfinite(want)).all(), func
        fin = np.isfinite(want)
        assert fin.any()
        np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4,
                                   atol=1e-6)

    def test_quantile_and_mad_served_from_grid(self):
        """Sort-network ops serve dense data from the grid; the quantile
        rides GridQuery.farg."""
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, _ = _mk_shard()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        for func, fargs in ((F.QUANTILE_OVER_TIME, (0.9,)),
                            (F.MAD_OVER_TIME, ()),
                            (F.HOLT_WINTERS, (0.3, 0.1))):
            got = shard.scan_grid(res.part_ids, func, steps0, nsteps, STEP,
                                  WINDOW, fargs=fargs)
            assert got is not None, func
            tags, vals, _tops = got
            end = steps0 + (nsteps - 1) * STEP
            t2, batch = shard.scan_batch(res.part_ids, steps0 - WINDOW, end)
            want = np.asarray(rangefns.apply_range_function(
                batch, StepRange(steps0, end, STEP), WINDOW, func,
                fargs))[:len(tags)]
            got_v = np.asarray(vals)
            fin = np.isfinite(want)
            assert fin.any()
            assert (np.isfinite(got_v) == fin).all(), func
            np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4)

    def test_adjacency_ops_gappy_fall_back(self):
        ms, shard, _ = _mk_shard(n_series=4, n_rows=50)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        tags = {"__name__": "req_total", "instance": "gappy", "_ws_": "w",
                "_ns_": "n"}
        for c in range(0, 50, 2):
            b.add(T0 + (c - 1) * STEP + 10, [float(c)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), 800 + off)
        shard.flush_all()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        # adjacency ops decline on gappy data; stddev (masked) still serves
        assert shard.scan_grid(res.part_ids, F.CHANGES, steps0, nsteps,
                               STEP, WINDOW) is None
        assert shard.scan_grid(res.part_ids, F.IRATE, steps0, nsteps,
                               STEP, WINDOW) is None
        assert shard.scan_grid(res.part_ids, F.STDDEV_OVER_TIME, steps0,
                               nsteps, STEP, WINDOW) is not None

    def test_large_window_gappy_falls_back(self):
        ms, shard, _ = _mk_shard(n_series=4, n_rows=200)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        tags = {"__name__": "req_total", "instance": "gappy", "_ws_": "w",
                "_ns_": "n"}
        for c in range(0, 200, 2):
            b.add(T0 + (c - 1) * STEP + 10, [float(c)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), 900 + off)
        shard.flush_all()
        res = _lookup(shard)
        assert shard.scan_grid(res.part_ids, F.RATE, T0 + 120 * STEP, 40,
                               STEP, 120 * STEP) is None
        # the failed dense proof is memoized: the repeat attempt is
        # denied up-front (no speculative block staging), and new data
        # (epoch bump) re-enables the attempt
        cache = next(iter(shard.device_caches.values()))
        builds0 = cache.builds
        assert shard.scan_grid(res.part_ids, F.RATE, T0 + 120 * STEP, 40,
                               STEP, 120 * STEP) is None
        assert cache.builds == builds0
        assert any(k[:3] == (F.RATE, 120 * STEP, STEP)
                   for k in cache._bigk_deny)

    def test_irregular_series_disables_grid(self):
        # two samples in one bucket violate the layout invariant
        ms, shard, _ = _mk_shard(n_series=2, n_rows=20)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        tags = {"__name__": "req_total", "instance": "burst", "_ws_": "w",
                "_ns_": "n"}
        b.add(T0 + 100 * STEP + 1, [1.0], tags)
        b.add(T0 + 100 * STEP + 2, [2.0], tags)   # same bucket
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), 500 + off)
        shard.flush_all()
        res = _lookup(shard)
        steps0 = T0 + 100 * STEP
        assert shard.scan_grid(res.part_ids, F.RATE, steps0, 4, STEP,
                               WINDOW) is None

    def test_eviction_under_budget(self):
        """Reclaim-on-demand: blocks pinned by the in-flight query survive,
        and a later narrow query evicts the oldest blocks past the budget."""
        # compression off: this test exercises the eviction mechanics,
        # and compressed blocks would fit the tiny budget outright
        ms, shard, _ = _mk_shard(n_rows=300, device_cache_bytes=300_000,
                                 device_cache_compress=False)
        res = _lookup(shard)
        steps0, nsteps = _steps(300)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None
        cache = next(iter(shard.device_caches.values()))
        full_blocks = len(cache.blocks)
        assert full_blocks >= 2
        # narrow recent query: older blocks become evictable
        recent0 = steps0 + (nsteps - 5) * STEP
        got = shard.scan_grid(res.part_ids, F.RATE, recent0, 4, STEP, WINDOW)
        assert got is not None
        assert cache.evictions > 0
        assert len(cache.blocks) < full_blocks


class TestEndToEndGridServing:
    def test_exec_plan_uses_grid(self):
        """The leaf + mapper pipeline serves from the device grid and the
        result matches the fallback path end to end."""
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec)
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import PeriodicSamplesMapper

        ms, shard, _ = _mk_shard()
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP

        def run():
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - WINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end,
                window_ms=WINDOW, function=F.RATE))
            return leaf.execute(ExecContext(ms, QueryContext()))

        r1 = run()
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1, "grid path was not used"
        builds = cache.builds
        r2 = run()
        assert cache.builds == builds          # repeat: zero uploads
        v1 = r1.batches[0].values
        v2 = r2.batches[0].values
        np.testing.assert_allclose(v1[np.isfinite(v1)], v2[np.isfinite(v2)])


class TestGridAggregatedServing:
    """Fused agg-on-device serving (scan_rate_grouped): only [G, T]
    partials cross the host link; results must match the per-series
    grid path + host aggregation exactly."""

    @pytest.mark.parametrize("op,agg_name", [
        ("sum", "SUM"), ("count", "COUNT"), ("avg", "AVG"),
        ("min", "MIN"), ("max", "MAX")])
    def test_exec_fused_agg_matches_host_agg(self, op, agg_name):
        from filodb_tpu.query.aggregators import AggPartialBatch
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec,
                                           ReduceAggregateExec)
        from filodb_tpu.query.logical import AggregationOperator
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import (AggregateMapReduce,
                                                   AggregatePresenter,
                                                   PeriodicSamplesMapper)

        ms, shard, _ = _mk_shard(n_series=10)
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP
        operator = AggregationOperator[agg_name]

        def run(grouped: bool):
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - WINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end,
                window_ms=WINDOW, function=F.RATE))
            if grouped:
                leaf.add_transformer(AggregateMapReduce(
                    operator, by=("instance",)))
            root = ReduceAggregateExec([leaf], operator) if grouped \
                else None
            if grouped:
                root.add_transformer(AggregatePresenter(operator))
                return root.execute(ExecContext(ms, QueryContext()))
            return leaf.execute(ExecContext(ms, QueryContext()))

        result = run(True)
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1
        got = {}
        for b in result.batches:
            for tags, ts, vals in b.to_series():
                got[tags["instance"]] = np.asarray(vals)
        # oracle: per-series grid path, aggregated on host per instance
        raw = run(False)
        want = {}
        pb = raw.batches[0]
        for tags, ts, vals in pb.to_series():
            want[tags["instance"]] = np.asarray(vals)
        assert set(got) == set(want)
        for k in want:
            # by (instance): each group has ONE member, so every op
            # reduces to the member itself (count -> 1 where finite)
            w = want[k]
            if agg_name == "COUNT":
                w = np.where(np.isfinite(w), 1.0, np.nan)
            np.testing.assert_allclose(got[k], w, rtol=1e-5,
                                       equal_nan=True)

    def test_fused_global_sum_matches(self):
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec,
                                           ReduceAggregateExec)
        from filodb_tpu.query.logical import AggregationOperator
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import (AggregateMapReduce,
                                                   AggregatePresenter,
                                                   PeriodicSamplesMapper)

        ms, shard, _ = _mk_shard(n_series=8)
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP

        def mk(with_grid: bool):
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - WINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end,
                window_ms=WINDOW, function=F.RATE))
            leaf.add_transformer(AggregateMapReduce(
                AggregationOperator.SUM))
            root = ReduceAggregateExec([leaf], AggregationOperator.SUM)
            root.add_transformer(AggregatePresenter(AggregationOperator.SUM))
            return root

        fused = mk(True).execute(ExecContext(ms, QueryContext()))
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1
        # disable the grid -> host fallback oracle
        cache.disabled_until_version = shard.ingest_epoch + 10**9
        plain = mk(False).execute(ExecContext(ms, QueryContext()))
        vf = np.asarray(fused.batches[0].values[0])
        vp = np.asarray(plain.batches[0].values[0])
        fin = np.isfinite(vp)
        assert (np.isfinite(vf) == fin).all()
        np.testing.assert_allclose(vf[fin], vp[fin], rtol=1e-4)


class TestHistGridServing:
    """First-class histogram columns on the device grid: each partition
    slot spans hb bucket lanes; the scalar kernel computes per-bucket
    rates (reference: per-bucket HistRateFunction + HistSumRowAggregator
    fused on device)."""

    HSTEP = 10_000
    HWINDOW = 50_000
    HK = 5

    def _mk_hist_shard(self, n_series=3, n_rows=60):
        from tests.data import START_TS, histogram_containers
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        for off, c in enumerate(histogram_containers(
                n_series=n_series, n_samples=n_rows)):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        return ms, shard, START_TS

    def test_hist_rate_matches_host_kernel(self):
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns

        ms, shard, t0 = self._mk_hist_shard()
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("req_latency"))], 0, 2**62)
        steps0 = t0 + (self.HK - 1) * self.HSTEP
        nsteps = 40
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps,
                              self.HSTEP, self.HWINDOW)
        assert got is not None, "hist grid should serve this query"
        tags, vals, tops = got
        assert vals.ndim == 3 and vals.shape[2] == len(tops)
        cache = next(iter(shard.device_caches.values()))
        assert cache.hist and cache.hits > 0 and cache.dense_hits > 0
        # oracle: scan_batch + the host per-bucket kernel
        end = steps0 + (nsteps - 1) * self.HSTEP
        t2, batch = shard.scan_batch(res.part_ids, steps0 - self.HWINDOW,
                                     end)
        sr = StepRange(steps0, end, self.HSTEP)
        want = np.asarray(rangefns.apply_range_function(
            batch, sr, self.HWINDOW, F.RATE))[:len(tags)]
        got_v = np.asarray(vals)
        assert (np.isfinite(got_v) == np.isfinite(want)).all()
        fin = np.isfinite(want)
        np.testing.assert_allclose(got_v[fin], want[fin], rtol=1e-4)

    def test_fused_hist_sum_quantile_matches_host(self):
        """sum(rate(latency[w])) + histogram_quantile fully on the grid
        (BASELINE config 2) vs the disabled-grid host oracle."""
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec,
                                           ReduceAggregateExec)
        from filodb_tpu.query.logical import (AggregationOperator,
                                              InstantFunctionId)
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import (
            AggregateMapReduce, AggregatePresenter,
            InstantVectorFunctionMapper, PeriodicSamplesMapper)

        ms, shard, t0 = self._mk_hist_shard()
        steps0 = t0 + (self.HK - 1) * self.HSTEP
        nsteps = 40
        end = steps0 + (nsteps - 1) * self.HSTEP

        def mk():
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_latency"))],
                steps0 - self.HWINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=self.HSTEP, end_ms=end,
                window_ms=self.HWINDOW, function=F.RATE))
            leaf.add_transformer(AggregateMapReduce(AggregationOperator.SUM))
            root = ReduceAggregateExec([leaf], AggregationOperator.SUM)
            root.add_transformer(AggregatePresenter(AggregationOperator.SUM))
            root.add_transformer(InstantVectorFunctionMapper(
                InstantFunctionId.HISTOGRAM_QUANTILE, (0.9,)))
            return root

        fused = mk().execute(ExecContext(ms, QueryContext()))
        cache = next(iter(shard.device_caches.values()))
        assert cache.hist and cache.hits >= 1
        cache.disabled_until_version = shard.ingest_epoch + 10**9
        plain = mk().execute(ExecContext(ms, QueryContext()))
        vf = np.asarray(fused.batches[0].np_values()[0])
        vp = np.asarray(plain.batches[0].np_values()[0])
        fin = np.isfinite(vp)
        assert fin.any()
        assert (np.isfinite(vf) == fin).all()
        np.testing.assert_allclose(vf[fin], vp[fin], rtol=1e-4)

    def test_hist_unsupported_fn_falls_back(self):
        ms, shard, t0 = self._mk_hist_shard()
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("req_latency"))], 0, 2**62)
        steps0 = t0 + (self.HK - 1) * self.HSTEP
        # min_over_time has no histogram semantics: grid must decline
        assert shard.scan_grid(res.part_ids, F.MIN_OVER_TIME, steps0, 10,
                               self.HSTEP, self.HWINDOW) is None


class TestGridOverTimeServing:
    """The widened grid fast path (_over_time family + bare instant
    selectors) vs the general fallback, through the exec plan."""

    @pytest.mark.parametrize("func", [F.SUM_OVER_TIME, F.COUNT_OVER_TIME,
                                      F.AVG_OVER_TIME, F.MIN_OVER_TIME,
                                      F.MAX_OVER_TIME, F.LAST_OVER_TIME])
    def test_over_time_matches_fallback(self, func):
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec)
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import PeriodicSamplesMapper

        ms, shard, _ = _mk_shard()
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP

        def run():
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - WINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end,
                window_ms=WINDOW, function=func))
            return leaf.execute(ExecContext(ms, QueryContext()))

        served = run()
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1, f"{func} not served from the grid"
        cache.disabled_until_version = shard.ingest_epoch + 10**9
        fallback = run()
        for bs, bf in zip(served.batches, fallback.batches):
            vs, vf = np.asarray(bs.values), np.asarray(bf.values)
            vs = vs[:len(bs.keys)]
            vf = vf[:len(bf.keys)]
            assert (np.isfinite(vs) == np.isfinite(vf)).all(), func
            both = np.isfinite(vs)
            np.testing.assert_allclose(vs[both], vf[both], rtol=1e-4,
                                       err_msg=str(func))

    def test_instant_selector_served_from_grid(self):
        """A bare selector (no window/function) uses the staleness
        lookback; the grid serves it as a last-sample scan."""
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec)
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import PeriodicSamplesMapper

        ms, shard, _ = _mk_shard()
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP

        def run():
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - 300_000, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end))
            return leaf.execute(ExecContext(ms, QueryContext()))

        served = run()
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1, "instant selector not grid-served"
        cache.disabled_until_version = shard.ingest_epoch + 10**9
        fallback = run()
        vs = np.asarray(served.batches[0].values)[:6]
        vf = np.asarray(fallback.batches[0].values)[:6]
        assert (np.isfinite(vs) == np.isfinite(vf)).all()
        both = np.isfinite(vs)
        np.testing.assert_allclose(vs[both], vf[both], rtol=1e-4)

    def test_fused_agg_over_time(self):
        """sum(sum_over_time(...)) fuses the aggregate on device too."""
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec,
                                           ReduceAggregateExec)
        from filodb_tpu.query.logical import AggregationOperator
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import (AggregateMapReduce,
                                                   AggregatePresenter,
                                                   PeriodicSamplesMapper)

        ms, shard, _ = _mk_shard(n_series=8)
        steps0, nsteps = _steps(50)
        end = steps0 + (nsteps - 1) * STEP

        def mk():
            leaf = MultiSchemaPartitionsExec(
                "prom", 0, [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - WINDOW, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=STEP, end_ms=end,
                window_ms=WINDOW, function=F.SUM_OVER_TIME))
            leaf.add_transformer(AggregateMapReduce(AggregationOperator.SUM))
            root = ReduceAggregateExec([leaf], AggregationOperator.SUM)
            root.add_transformer(AggregatePresenter(AggregationOperator.SUM))
            return root

        fused = mk().execute(ExecContext(ms, QueryContext()))
        cache = next(iter(shard.device_caches.values()))
        assert cache.hits >= 1
        cache.disabled_until_version = shard.ingest_epoch + 10**9
        plain = mk().execute(ExecContext(ms, QueryContext()))
        vf = np.asarray(fused.batches[0].values[0])
        vp = np.asarray(plain.batches[0].values[0])
        fin = np.isfinite(vp)
        assert (np.isfinite(vf) == fin).all()
        np.testing.assert_allclose(vf[fin], vp[fin], rtol=1e-4)


class TestDownsampledGridServing:
    """Downsampled datasets are aligned by construction (period-end
    timestamps at exact resolution multiples), so the grid fast path
    serves long-range queries routed to them (reference intent:
    DownsampledTimeSeriesShard serving from block memory)."""

    def test_ds_dataset_served_from_grid(self):
        from filodb_tpu.downsample.sharddown import MemoryDownsamplePublisher
        from filodb_tpu.downsample.dsstore import (DownsampledTimeSeriesStore,
                                                   ds_dataset_name)
        from filodb_tpu.query.exec import (ExecContext,
                                           MultiSchemaPartitionsExec)
        from filodb_tpu.query.model import QueryContext
        from filodb_tpu.query.transformers import PeriodicSamplesMapper

        RES = 60_000
        pub = MemoryDownsamplePublisher()
        _, shard2, _ = _mk_shard(n_series=5, n_rows=120,
                                 jitter_max=5_000, flush=False)
        shard2.enable_downsampling(pub, (RES,))
        shard2.flush_all()   # emits downsample records to the publisher

        ds = DownsampledTimeSeriesStore("prom", resolutions_ms=(RES,))
        ds.setup(DEFAULT_SCHEMAS, 0)
        assert ds.ingest_from_publisher(pub) > 0
        ds_shard = ds.shard(RES, 0)
        ds_shard.flush_all()   # freeze so the grid builds from chunks

        # query at the resolution step: avg_over_time over the ds series
        lookup = ds_shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("req_total"))], 0, 2**62)
        assert len(lookup.part_ids) == 5
        t_lo = min(p.earliest_timestamp
                   for p in ds_shard.partitions.values())
        steps0 = ((t_lo // RES) + 6) * RES
        end = steps0 + 30 * RES

        def run():
            leaf = MultiSchemaPartitionsExec(
                ds_dataset_name("prom", RES), 0,
                [ColumnFilter("_metric_", Equals("req_total"))],
                steps0 - 5 * RES, end)
            leaf.add_transformer(PeriodicSamplesMapper(
                start_ms=steps0, step_ms=RES, end_ms=end,
                window_ms=5 * RES, function=F.AVG_OVER_TIME))
            return leaf.execute(ExecContext(ds.memstore, QueryContext()))

        served = run()
        cache = next(iter(ds_shard.device_caches.values()))
        assert cache.hits >= 1, "ds dataset not served from the grid"
        cache.disabled_until_version = ds_shard.ingest_epoch + 10**9
        fallback = run()
        vs = np.asarray(served.batches[0].values)[:5]
        vf = np.asarray(fallback.batches[0].values)[:5]
        assert (np.isfinite(vs) == np.isfinite(vf)).all()
        both = np.isfinite(vs)
        np.testing.assert_allclose(vs[both], vf[both], rtol=1e-4)


class TestUniformPhaseServing:
    """Uniform-phase serving: per-lane constant scrape offsets let the
    grid drop the ts plane (ops/grid.py PHASE_OPS).  The proof must
    activate on fixed-cadence data, produce results identical to the
    general path, and stay OFF for per-sample-jittered data."""

    def _mk_uniform(self, n_series=6, n_rows=50, seed=3):
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0, StoreConfig())
        rng = np.random.default_rng(seed)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        truth = {}
        phases = rng.integers(1, STEP, n_series)
        for i in range(n_series):
            tags = {"__name__": "req_total", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            base = T0 + np.arange(n_rows, dtype=np.int64) * STEP - STEP
            ts = base + phases[i]          # constant per-series phase
            vals = np.cumsum(rng.random(n_rows) * 5)
            if i == 1:
                vals[n_rows // 2:] -= vals[n_rows // 2] * 0.9  # reset
            truth[f"i{i}"] = (ts, vals)
            for t, v in zip(ts, vals):
                b.add(int(t), [float(v)], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        return ms, shard, truth

    def _oracle_rate(self, shard, part_ids, steps0, nsteps):
        from filodb_tpu.ops.windows import StepRange
        from filodb_tpu.query import rangefns
        t2, batch = shard.scan_batch(part_ids, steps0 - WINDOW,
                                     steps0 + (nsteps - 1) * STEP)
        sr = StepRange(steps0, steps0 + (nsteps - 1) * STEP, STEP)
        want = np.asarray(rangefns.apply_range_function(
            batch, sr, WINDOW, F.RATE))
        return t2, want[:len(t2)]

    def test_phase_serving_matches_general(self):
        ms, shard, truth = self._mk_uniform()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None
        tags, vals, _ = got
        cache = next(iter(shard.device_caches.values()))
        assert cache._phase_memo, "uniform-phase proof should activate"
        t2, want = self._oracle_rate(shard, res.part_ids, steps0, nsteps)
        by_inst = {t["instance"]: i for i, t in enumerate(t2)}
        for i, tg in enumerate(tags):
            w = want[by_inst[tg["instance"]]]
            both = np.isfinite(vals[i]) & np.isfinite(w)
            assert (np.isfinite(vals[i]) == np.isfinite(w)).all()
            np.testing.assert_allclose(vals[i][both], w[both], rtol=2e-5)

    def test_phase_proof_rejects_jitter(self):
        ms, shard, truth = _mk_shard(jitter_max=30_000)
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        got = shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None          # ts path still serves
        cache = next(iter(shard.device_caches.values()))
        assert not cache._phase_memo, "jittered data must not prove phase"

    def test_phase_memo_reused_on_repeat(self):
        import jax
        ms, shard, truth = self._mk_uniform()
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP, WINDOW)
        cache = next(iter(shard.device_caches.values()))
        assert cache._phase_memo
        (key, (host, dev)) = next(iter(cache._phase_memo.items()))
        shard.scan_grid(res.part_ids, F.RATE, steps0, nsteps, STEP, WINDOW)
        (key2, (host2, dev2)) = next(iter(cache._phase_memo.items()))
        assert key2 == key and dev2 is dev, "repeat must not re-upload"

    def test_grouped_phase_serving_matches(self):
        ms, shard, truth = self._mk_uniform(n_series=8)
        res = _lookup(shard)
        steps0, nsteps = _steps(50)
        gids = [0, 1] * 4
        state = shard.scan_grid_grouped(res.part_ids, F.RATE, steps0,
                                        nsteps, STEP, WINDOW, gids, 2,
                                        "sum")
        assert state is not None
        t2, want = self._oracle_rate(shard, res.part_ids, steps0, nsteps)
        by_inst = {t["instance"]: i for i, t in enumerate(t2)}
        order = [by_inst[f"i{i}"] for i in range(8)]
        for g in range(2):
            rows = want[[order[i] for i in range(8) if gids[i] == g]]
            exp = np.nansum(np.where(np.isfinite(rows), rows, 0.0), axis=0)
            np.testing.assert_allclose(state["sum"][g], exp, rtol=2e-5)


class TestCompressedResidents:
    """Round-5 VERDICT #4: grid blocks stay compressed in HBM (XOR-class
    value planes + elided uniform-phase ts planes) and decode on device
    inside the serving program — results must be BIT-IDENTICAL to the
    decoded-plane path, and realistic (integer-valued) gauges must fit
    >=4x more resident window per HBM byte."""

    def _gauge_shard(self, compress: bool, n_series=8, n_rows=96):
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0,
                         StoreConfig(device_cache_compress=compress))
        rng = np.random.default_rng(5)
        b = RecordBuilder(DEFAULT_SCHEMAS["gauge"])
        for i in range(n_series):
            tags = {"__name__": "g_res", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            ts = T0 + np.arange(n_rows, dtype=np.int64) * STEP
            # integer-valued gauge (bytes/requests/connections — the
            # common shape): a bounded random walk around 1e6
            vals = (1_000_000 + np.cumsum(
                rng.integers(-500, 500, size=n_rows))).astype(np.float64)
            b.add_series(ts, [vals], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        return ms, shard

    def _serve_all(self, shard, n_rows):
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("g_res"))], 0, 2**62)
        steps0 = T0 + (K + 1) * STEP
        nsteps = n_rows - K - 2
        out = {}
        for fn in (F.RATE, F.SUM_OVER_TIME, F.MAX_OVER_TIME, None):
            got = shard.scan_grid(res.part_ids, fn, steps0, nsteps,
                                  STEP, WINDOW)
            assert got is not None, fn
            tags_l, vals, _ = got
            order = np.argsort([t["instance"] for t in tags_l])
            out[fn] = np.asarray(vals)[order]
        return out

    def test_bit_identical_to_decoded_path(self):
        _ms1, compressed = self._gauge_shard(True)
        _ms2, plain = self._gauge_shard(False)
        got_c = self._serve_all(compressed, 96)
        got_p = self._serve_all(plain, 96)
        cache = next(iter(compressed.device_caches.values()))
        # the compressed store must actually hold packed blocks with an
        # elided ts plane (uniform cadence, integral values)
        assert any(isinstance(b.vals, dict) for b in cache.blocks.values())
        assert any(b.ts is None for b in cache.blocks.values())
        for fn in got_p:
            np.testing.assert_array_equal(got_c[fn], got_p[fn],
                                          err_msg=str(fn))

    def test_resident_window_at_least_4x(self):
        _ms, shard = self._gauge_shard(True, n_series=64, n_rows=128)
        self._serve_all(shard, 128)
        cache = next(iter(shard.device_caches.values()))
        raw = comp = 0
        from filodb_tpu.memstore.devicestore import BLOCK_BUCKETS
        for b in cache.blocks.values():
            rows = BLOCK_BUCKETS
            itemsize = 8 if not isinstance(b.vals, dict) \
                else b.vals["raw"].dtype.itemsize
            raw += rows * b.width * (4 + itemsize)
            comp += b.nbytes
        assert comp > 0 and raw / comp >= 4.0, (raw, comp, raw / comp)

    def test_repeat_query_no_rebuild_compressed(self):
        _ms, shard = self._gauge_shard(True)
        self._serve_all(shard, 96)
        cache = next(iter(shard.device_caches.values()))
        builds = cache.builds
        self._serve_all(shard, 96)
        assert cache.builds == builds, "repeat query rebuilt blocks"


class TestFusedPackedServing:
    """ISSUE 3 tentpole: eligible queries over a compressed resident run
    the FUSED packed kernels (XOR-class decode inside the grid kernel,
    interpret mode on CPU CI) and must match the decoded-plane path —
    bit-identical for free ops, to f32 rounding for the MXU rate chain.
    Also covers the hbm_read_bytes accounting satellite."""

    @pytest.fixture()
    def f32_interpret(self, monkeypatch):
        from filodb_tpu.memstore import devicestore
        monkeypatch.setattr(devicestore, "_PACKED_INTERPRET", True)
        monkeypatch.setattr(devicestore, "_PACKED_BROKEN", False)
        monkeypatch.setattr(devicestore.DeviceGridCache, "_val_dtype",
                            lambda self: np.float32)
        return devicestore

    def _counter_shard(self, compress: bool, n_rows=96, n_series=8):
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0,
                         StoreConfig(device_cache_compress=compress))
        rng = np.random.default_rng(7)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-counter"])
        for i in range(n_series):
            tags = {"__name__": "c_total", "instance": f"i{i}",
                    "_ws_": "w", "_ns_": "n"}
            ph = int(rng.integers(1, STEP))
            ts = T0 + np.arange(n_rows, dtype=np.int64) * STEP - STEP + ph
            vals = (2 ** 23 + 128 * np.cumsum(
                rng.integers(1, 8, n_rows))).astype(np.float64)
            b.add_series(ts, [vals], tags)
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        return ms, shard

    def _scan(self, shard, fn, n_rows=96):
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("c_total"))], 0, 2**62)
        steps0 = T0 + (K + 1) * STEP
        nsteps = n_rows - K - 2
        got = shard.scan_grid(res.part_ids, fn, steps0, nsteps, STEP,
                              WINDOW)
        assert got is not None, fn
        tags_l, vals, _ = got
        order = np.argsort([t["instance"] for t in tags_l])
        return np.asarray(vals)[order]

    def test_fused_packed_dispatch_and_equivalence(self, f32_interpret):
        devicestore = f32_interpret
        _ms1, comp = self._counter_shard(True)
        _ms2, plain = self._counter_shard(False)
        for fn, exact in ((F.SUM_OVER_TIME, True), (F.MAX_OVER_TIME, True),
                          (None, True), (F.RATE, False)):
            got_c = self._scan(comp, fn)
            got_p = self._scan(plain, fn)
            if exact:
                np.testing.assert_array_equal(got_c, got_p,
                                              err_msg=str(fn))
            else:
                # MXU correction formulation vs the CPU roll-scan ref
                fin = np.isfinite(got_p)
                assert (np.isfinite(got_c) == fin).all()
                np.testing.assert_allclose(got_c[fin], got_p[fin],
                                           rtol=1e-6)
        cache = next(iter(comp.device_caches.values()))
        plan = next(iter(cache._plan_memo.values()))
        assert plan.packed is not None, \
            "compressed single-block query did not take the fused path"
        assert not devicestore._PACKED_BROKEN
        assert plan.hbm_comp > 0 and plan.hbm_dense == 0

    def test_fused_grouped_matches_decoded(self, f32_interpret):
        _ms1, comp = self._counter_shard(True)
        _ms2, plain = self._counter_shard(False)
        gids = [0, 1] * 4
        outs = []
        for shard in (comp, plain):
            res = shard.lookup_partitions(
                [ColumnFilter("_metric_", Equals("c_total"))], 0, 2**62)
            steps0 = T0 + (K + 1) * STEP
            st = shard.scan_grid_grouped(res.part_ids, F.RATE, steps0,
                                         96 - K - 2, STEP, WINDOW, gids,
                                         2, "sum")
            assert st is not None
            outs.append(st)
        np.testing.assert_allclose(outs[0]["sum"], outs[1]["sum"],
                                   rtol=1e-6)
        np.testing.assert_array_equal(outs[0]["count"], outs[1]["count"])

    def test_hbm_read_bytes_reach_query_stats(self, f32_interpret):
        from filodb_tpu.query import exec as qexec
        from filodb_tpu.query.model import QueryStats
        _ms, shard = self._counter_shard(True)
        ctx = qexec.ExecContext(memstore=None)
        qexec._ACTIVE.ctx = ctx
        try:
            self._scan(shard, F.SUM_OVER_TIME)
        finally:
            qexec._ACTIVE.ctx = None
        stats = QueryStats()
        ctx.fold_into(stats)
        assert stats.hbm_read_bytes.get("compressed", 0) > 0
        assert "dense" not in stats.hbm_read_bytes
        # and the counter family is registered under filodb_query_*
        from filodb_tpu.utils.observability import query_metrics
        m = query_metrics()["hbm_read_bytes"]
        assert m is not None

    def test_broken_breaker_falls_back(self, f32_interpret, monkeypatch):
        """A failing fused dispatch must trip the breaker and serve
        through the XLA decode path, not error the query."""
        devicestore = f32_interpret
        _ms, shard = self._counter_shard(True)

        calls = []

        def boom(*a, **k):
            calls.append(1)
            raise RuntimeError("mosaic rejected the kernel")
        devicestore._fused_progs()       # ensure progs exist, then break
        monkeypatch.setitem(devicestore._FUSED_PROGS, "series_packed",
                            boom)
        out = self._scan(shard, F.SUM_OVER_TIME)
        assert np.isfinite(out).any()
        assert devicestore._PACKED_BROKEN
        assert len(calls) == 1
        # memoized plans keep .packed set; the tripped breaker must
        # short-circuit instead of re-attempting the failing build
        out2 = self._scan(shard, F.SUM_OVER_TIME)
        assert np.isfinite(out2).any()
        assert len(calls) == 1, "breaker re-dispatched the broken kernel"


class TestFusedPackedHistServing:
    """ISSUE 14 tentpole: the ``not self.hist`` gate is lifted —
    histogram bucket planes serve from packed compressed residents
    through the SAME fused kernels (bucket columns are packed lanes;
    the ``lane*hb + bucket`` indirection composes through the pack's
    ``inv``), bit-equal to the XLA decode path, with the dedicated
    ``compressed-hist`` HBM format accounted."""

    HB = 8
    HSTEP = 10_000
    HK = 5

    @pytest.fixture()
    def f32_interpret(self, monkeypatch):
        from filodb_tpu.memstore import devicestore
        monkeypatch.setattr(devicestore, "_PACKED_INTERPRET", True)
        monkeypatch.setattr(devicestore, "_PACKED_BROKEN", False)
        monkeypatch.setattr(devicestore.DeviceGridCache, "_val_dtype",
                            lambda self: np.float32)
        return devicestore

    def _hist_shard(self, compress: bool, n_series=4, n_rows=96, seed=3):
        from filodb_tpu.codecs import histcodec
        from filodb_tpu.core.histogram import GeometricBuckets
        ms = TimeSeriesMemStore()
        shard = ms.setup("prom", DEFAULT_SCHEMAS, 0,
                         StoreConfig(device_cache_compress=compress))
        rng = np.random.default_rng(seed)
        buckets = GeometricBuckets(2.0, 2.0, self.HB)
        b = RecordBuilder(DEFAULT_SCHEMAS["prom-histogram"])
        for s in range(n_series):
            ph = int(rng.integers(1, self.HSTEP))
            cum = np.zeros(self.HB, np.int64)
            for t in range(n_rows):
                # integer counts with a pinned f32 exponent: the pack's
                # 16-bit-class guarantee holds per bucket column
                cum += 128 * rng.integers(1, 8, self.HB)
                vals = 2 ** 23 + np.cumsum(cum)
                blob = histcodec.encode_hist_value(buckets, vals)
                b.add(T0 + t * self.HSTEP - self.HSTEP + ph,
                      (float(vals[-1]), float(vals[-1]), blob),
                      {"__name__": "lat", "instance": f"i{s}",
                       "_ws_": "w", "_ns_": "n"})
        for off, c in enumerate(b.containers()):
            shard.ingest(decode_container(c, DEFAULT_SCHEMAS), off)
        shard.flush_all()
        return ms, shard

    def _scan(self, shard, fn, n_rows=96):
        res = shard.lookup_partitions(
            [ColumnFilter("_metric_", Equals("lat"))], 0, 2**62)
        steps0 = T0 + (self.HK + 1) * self.HSTEP
        nsteps = n_rows - self.HK - 2
        got = shard.scan_grid(res.part_ids, fn, steps0, nsteps,
                              self.HSTEP, self.HK * self.HSTEP)
        assert got is not None, fn
        tags_l, vals, _tops = got
        order = np.argsort([t["instance"] for t in tags_l])
        return np.asarray(vals)[order]

    def test_hist_packed_dispatch_and_equivalence(self, f32_interpret):
        devicestore = f32_interpret
        _ms1, comp = self._hist_shard(True)
        _ms2, plain = self._hist_shard(False)
        for fn, exact in ((F.SUM_OVER_TIME, True), (None, True),
                          (F.RATE, False)):
            got_c = self._scan(comp, fn)
            got_p = self._scan(plain, fn)
            assert got_c.ndim == 3 and got_c.shape[2] == self.HB
            fin = np.isfinite(got_p)
            assert (np.isfinite(got_c) == fin).all(), fn
            if exact:
                np.testing.assert_array_equal(got_c, got_p,
                                              err_msg=str(fn))
            else:
                np.testing.assert_allclose(got_c[fin], got_p[fin],
                                           rtol=1e-6)
        cache = next(iter(comp.device_caches.values()))
        assert cache.hist
        plan = next(iter(cache._plan_memo.values()))
        assert plan.packed is not None, \
            "compressed hist block did not take the fused packed path"
        assert not devicestore._PACKED_BROKEN
        assert plan.hbm_comp_hist > 0 and plan.hbm_dense == 0 \
            and plan.hbm_comp == 0

    def test_hist_grouped_fused_matches_decoded(self, f32_interpret):
        _ms1, comp = self._hist_shard(True)
        _ms2, plain = self._hist_shard(False)
        gids = [0, 1, 0, 1]
        outs = []
        for shard in (comp, plain):
            res = shard.lookup_partitions(
                [ColumnFilter("_metric_", Equals("lat"))], 0, 2**62)
            steps0 = T0 + (self.HK + 1) * self.HSTEP
            st = shard.scan_grid_grouped(
                res.part_ids, F.RATE, steps0, 96 - self.HK - 2,
                self.HSTEP, self.HK * self.HSTEP, gids, 2, "sum")
            assert st is not None
            outs.append(st)
        np.testing.assert_allclose(outs[0]["hist_sum"],
                                   outs[1]["hist_sum"], rtol=1e-6)
        np.testing.assert_array_equal(outs[0]["count"], outs[1]["count"])
        np.testing.assert_array_equal(outs[0]["bucket_tops"],
                                      outs[1]["bucket_tops"])

    def test_compressed_hist_format_reaches_query_stats(self,
                                                        f32_interpret):
        from filodb_tpu.query import exec as qexec
        from filodb_tpu.query.model import QueryStats
        _ms, shard = self._hist_shard(True)
        ctx = qexec.ExecContext(memstore=None)
        qexec._ACTIVE.ctx = ctx
        try:
            self._scan(shard, F.SUM_OVER_TIME)
        finally:
            qexec._ACTIVE.ctx = None
        stats = QueryStats()
        ctx.fold_into(stats)
        assert stats.hbm_read_bytes.get("compressed-hist", 0) > 0
        assert "dense" not in stats.hbm_read_bytes
        # the packed planes must read FEWER bytes per sample than the
        # dense plane would (the acceptance criterion's lower-hbm proof)
        cache = next(iter(shard.device_caches.values()))
        from filodb_tpu.memstore.devicestore import BLOCK_BUCKETS
        dense_bytes = sum(BLOCK_BUCKETS * b.width * 4
                          for b in cache.blocks.values())
        assert 0 < stats.hbm_read_bytes["compressed-hist"] < dense_bytes
